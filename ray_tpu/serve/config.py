"""Serve configuration objects.

ray: python/ray/serve/config.py — DeploymentConfig / AutoscalingConfig /
HTTPOptions.  Kept as plain dataclasses; validation happens here so the
controller can trust what it stores.
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-depth autoscaling (ray: serve/_private/autoscaling_policy.py).

    desired = ceil(total_ongoing_requests / target_ongoing_requests),
    clamped to [min_replicas, max_replicas]; scale decisions are debounced
    by upscale_delay_s / downscale_delay_s of consistent signal.
    """

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 3.0

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError("need 0 <= min_replicas <= max_replicas")
        if self.target_ongoing_requests <= 0:
            raise ValueError("target_ongoing_requests must be > 0")


@dataclass
class DeploymentConfig:
    """Per-deployment target state held by the controller
    (ray: serve/config.py DeploymentConfig)."""

    num_replicas: int = 1
    max_concurrent_queries: int = 8
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 0.25
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if isinstance(self.autoscaling_config, dict):
            self.autoscaling_config = AutoscalingConfig(**self.autoscaling_config)
        if self.num_replicas < 0:
            raise ValueError("num_replicas must be >= 0")
        if self.max_concurrent_queries < 1:
            raise ValueError("max_concurrent_queries must be >= 1")

    def to_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentConfig":
        d = dict(d)
        ac = d.get("autoscaling_config")
        if isinstance(ac, dict):
            d["autoscaling_config"] = AutoscalingConfig(**ac)
        return cls(**d)


@dataclass
class HTTPOptions:
    """ray: serve/config.py HTTPOptions. port=0 picks a free port."""

    host: str = "127.0.0.1"
    port: int = 8000
    # 0 = the serve_proxy_max_connections config knob.  Connections beyond
    # the bound are refused with 503 at accept (ray: uvicorn
    # limit-concurrency role).
    max_connections: int = 0


# Controller actor's well-known name (ray: serve/_private/constants.py
# SERVE_CONTROLLER_NAME).
SERVE_CONTROLLER_NAME = "_serve_controller"
SERVE_NAMESPACE = "_serve"
