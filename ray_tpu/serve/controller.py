"""ServeController: the reconciliation brain of Serve.

ray: python/ray/serve/controller.py:64 (ServeController; deploy :363) +
_private/deployment_state.py:962,1812 (DeploymentState(Manager) reconcile).
One named controller actor holds the target state for every deployment and
runs a background reconcile loop:

  target num_replicas  vs  live replicas  →  start / drain+kill
  health checks (pull)  →  dead replica   →  replace
  queue-depth metrics   →  autoscaler     →  adjust target within bounds

Routers learn membership by polling `get_routing_table(version)` — the
pull analogue of the reference's LongPollHost (long_poll.py:185): the
version bumps on every membership change, so callers cheaply detect "no
change" without shipping the table.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.replica import Replica


class _DeploymentState:
    """Controller-side record for one deployment
    (ray: deployment_state.py DeploymentState)."""

    def __init__(self, name: str, blob: bytes, init_args, init_kwargs, config: DeploymentConfig):
        self.name = name
        self.blob = blob
        self.init_args = init_args or ()
        self.init_kwargs = init_kwargs or {}
        self.config = config
        self.replicas: Dict[str, Any] = {}  # replica_id -> ActorHandle
        self.inflight_health: Dict[str, Any] = {}  # replica_id -> pending ref
        self.last_metrics: Dict[str, float] = {}  # replica_id -> ongoing
        self.autoscale_target: Optional[int] = None  # autoscaler's current decision
        self._scale_signal_since: Optional[float] = None
        self._scale_signal_dir = 0
        self._counter = 0

    def next_replica_id(self) -> str:
        self._counter += 1
        return f"{self.name}#{self._counter}"

    def target_replicas(self) -> int:
        if self.config.autoscaling_config is not None:
            ac = self.config.autoscaling_config
            if self.autoscale_target is None:
                self.autoscale_target = max(ac.min_replicas, min(self.config.num_replicas, ac.max_replicas))
            return self.autoscale_target
        return self.config.num_replicas


class ServeController:
    def __init__(self, reconcile_period_s: float = 0.25):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._lock = threading.RLock()
        self._version = 0
        # Long-poll push (ray: _private/long_poll.py:185 LongPollHost):
        # routers park a listen_for_change call on the SHARED pubsub
        # long-poll abstraction (pubsub.py — the same Publisher plane the
        # runtime and GCS use); every version bump notifies them, so
        # membership/config changes reach the data plane in push latency.
        from ray_tpu._private.pubsub import LongPollHost

        self._longpoll = LongPollHost()
        self._stop = threading.Event()
        self._period = reconcile_period_s
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconciler"
        )
        self._thread.start()

    def _bump_version_locked(self) -> None:
        self._version += 1
        self._longpoll.notify("routing", self._version)

    def listen_for_change(
        self, known_version: int, timeout_s: float = 30.0
    ) -> Optional[Dict[str, Any]]:
        """Park until the routing table moves past known_version (or the
        chunk timeout lapses — caller immediately re-listens).  Runs on one
        of the controller actor's concurrency slots
        (ray: LongPollHost.listen_for_change)."""
        moved = self._longpoll.wait_for_change(
            "routing",
            lambda: self._version > known_version or self._stop.is_set(),
            timeout_s,
        )
        if not moved or self._version <= known_version:
            return None
        return self.get_routing_table(known_version)

    # -- public control API (called by serve.api / routers) ----------------
    def deploy(
        self,
        name: str,
        callable_blob: bytes,
        init_args: tuple,
        init_kwargs: dict,
        config_dict: Dict[str, Any],
    ) -> None:
        config = DeploymentConfig.from_dict(config_dict)
        with self._lock:
            existing = self._deployments.get(name)
            if existing is None:
                self._deployments[name] = _DeploymentState(
                    name, callable_blob, init_args, init_kwargs, config
                )
            else:
                code_changed = callable_blob != existing.blob or (
                    (init_args, init_kwargs) != (existing.init_args, existing.init_kwargs)
                )
                user_config_changed = config.user_config != existing.config.user_config
                existing.blob = callable_blob
                existing.init_args = init_args or ()
                existing.init_kwargs = init_kwargs or {}
                existing.config = config
                existing.autoscale_target = None
                if code_changed:
                    # Code redeploy: replace every replica (reference does a
                    # rolling update; all-at-once keeps v0 simple & correct).
                    for rid, h in list(existing.replicas.items()):
                        self._drain_and_kill(existing, rid, h)
                elif user_config_changed and config.user_config is not None:
                    for h in existing.replicas.values():
                        h.reconfigure.remote(config.user_config)
            self._bump_version_locked()
        # Reconcile synchronously once so deploy() returning means "replicas
        # are starting" (tests and users can then poll wait_for_ready).
        self._reconcile_once()

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            st = self._deployments.pop(name, None)
            if st is not None:
                for rid, h in list(st.replicas.items()):
                    self._drain_and_kill(st, rid, h)
                self._bump_version_locked()

    def list_deployments(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: {
                    "target_replicas": st.target_replicas(),
                    "live_replicas": len(st.replicas),
                    "config": st.config.to_dict(),
                }
                for name, st in self._deployments.items()
            }

    def routing_version(self) -> int:
        return self._version

    def get_routing_table(
        self, known_version: int = -1
    ) -> Optional[Dict[str, Any]]:
        """Return {deployment: {replicas, max_concurrent_queries}}, or None
        when the caller's version is current (cheap no-change path)."""
        with self._lock:
            if known_version == self._version:
                return None
            table = {}
            for name, st in self._deployments.items():
                table[name] = {
                    "replicas": list(st.replicas.items()),
                    "max_concurrent_queries": st.config.max_concurrent_queries,
                }
            return {"version": self._version, "table": table}

    def wait_for_ready(self, name: str, timeout_s: float = 30.0) -> bool:
        """Block until the deployment has its target replica count live."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                st = self._deployments.get(name)
                if st is not None and len(st.replicas) >= st.target_replicas() > 0:
                    return True
            time.sleep(0.05)
        return False

    def shutdown(self) -> None:
        self._stop.set()
        with self._lock:
            for st in self._deployments.values():
                for rid, h in list(st.replicas.items()):
                    self._drain_and_kill(st, rid, h)
            self._deployments.clear()
            self._bump_version_locked()

    def ping(self) -> str:
        return "pong"

    # -- reconciliation -----------------------------------------------------
    def _start_replica(self, st: _DeploymentState) -> None:
        rid = st.next_replica_id()
        opts = dict(st.config.ray_actor_options or {})
        # +2 control slots: check_health / reconfigure / drain must answer
        # while all query slots are busy.
        handle = (
            ray_tpu.remote(Replica)
            .options(
                max_concurrency=st.config.max_concurrent_queries + 2,
                **opts,
            )
            .remote(
                st.name,
                rid,
                st.blob,
                st.init_args,
                st.init_kwargs,
                st.config.user_config,
            )
        )
        st.replicas[rid] = handle

    def _drain_and_kill(self, st: _DeploymentState, rid: str, handle) -> None:
        st.replicas.pop(rid, None)
        st.inflight_health.pop(rid, None)
        st.last_metrics.pop(rid, None)
        try:
            # Fire-and-forget drain, then kill. The drain ref is collected by
            # the kill below regardless of outcome.
            handle.prepare_for_shutdown.remote(st.config.graceful_shutdown_timeout_s)
            ray_tpu.kill(handle)
        except Exception:
            pass

    def _reconcile_once(self) -> None:
        with self._lock:
            states = list(self._deployments.values())
        changed = False
        for st in states:
            with self._lock:
                changed |= self._check_health(st)
                changed |= self._autoscale(st)
                target = st.target_replicas()
                live = len(st.replicas)
                if live < target:
                    for _ in range(target - live):
                        self._start_replica(st)
                    changed = True
                elif live > target:
                    # Drop the newest replicas first (oldest have warm caches /
                    # compiled programs — keep them).
                    doomed = sorted(st.replicas.keys())[target - live :]
                    for rid in doomed:
                        self._drain_and_kill(st, rid, st.replicas[rid])
                    changed = True
        if changed:
            with self._lock:
                self._bump_version_locked()
        self._publish_replica_targets()

    def _publish_replica_targets(self) -> None:
        """Publish {deployment: {target, live}} to GCS kv so the head's
        demand summary (and the elastic autoscaler behind it) can see serve
        capacity pressure without holding an actor handle to this
        controller.  Best-effort: the kv row is advisory demand telemetry,
        a missed publish just means the autoscaler acts one reconcile
        period later."""
        import json

        with self._lock:
            targets = {
                name: {
                    "target": st.target_replicas(),
                    "live": len(st.replicas),
                }
                for name, st in self._deployments.items()
            }
        try:
            from ray_tpu._private.client import client

            client.kv_put(
                "replica_targets",
                json.dumps(targets, sort_keys=True).encode(),
                namespace="serve",
            )
        except Exception:
            pass

    def _check_health(self, st: _DeploymentState) -> bool:
        """Pull-based health check (ray: gcs_health_check_manager.h:39 at the
        node level; serve replica checks at deployment_state.py).  Issues
        check_health to every replica, reaps answers next cycle."""
        changed = False
        # Collect previously issued checks.
        for rid, (ref, issued_at) in list(st.inflight_health.items()):
            if rid not in st.replicas:
                st.inflight_health.pop(rid)
                continue
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            if not ready:
                # Replicas that never answered a check yet are still
                # STARTING (jax import + first jit can take tens of seconds);
                # give them a generous grace before declaring them hung
                # (ray: deployment_state.py distinguishes STARTING from
                # RUNNING health checks).
                limit = st.config.health_check_timeout_s
                if rid not in st.last_metrics:
                    limit = max(limit, 120.0)
                if time.time() - issued_at > limit:
                    # Hung replica: treat as dead (ray: deployment_state.py
                    # health-check timeout path).
                    st.inflight_health.pop(rid)
                    h = st.replicas.pop(rid, None)
                    st.last_metrics.pop(rid, None)
                    if h is not None:
                        try:
                            ray_tpu.kill(h)
                        except Exception:
                            pass
                    changed = True
                continue
            st.inflight_health.pop(rid)
            try:
                m = ray_tpu.get(ref, timeout=1)
                st.last_metrics[rid] = float(m.get("ongoing", 0))
            except Exception:
                # Dead or failing replica: remove; the sizing pass replaces it.
                h = st.replicas.pop(rid, None)
                st.last_metrics.pop(rid, None)
                if h is not None:
                    try:
                        ray_tpu.kill(h)
                    except Exception:
                        pass
                changed = True
        # Issue fresh checks for replicas without one in flight.
        for rid, h in st.replicas.items():
            if rid not in st.inflight_health:
                try:
                    st.inflight_health[rid] = (h.check_health.remote(), time.time())
                except Exception:
                    changed = True
        return changed

    def _autoscale(self, st: _DeploymentState) -> bool:
        ac = st.config.autoscaling_config
        if ac is None or not st.replicas:
            return False
        total_ongoing = sum(st.last_metrics.get(rid, 0.0) for rid in st.replicas)
        desired = math.ceil(total_ongoing / ac.target_ongoing_requests)
        desired = max(ac.min_replicas, min(desired, ac.max_replicas))
        current = st.target_replicas()
        if desired == current:
            st._scale_signal_since = None
            st._scale_signal_dir = 0
            return False
        direction = 1 if desired > current else -1
        now = time.time()
        if st._scale_signal_dir != direction:
            st._scale_signal_dir = direction
            st._scale_signal_since = now
            return False
        delay = ac.upscale_delay_s if direction > 0 else ac.downscale_delay_s
        if now - (st._scale_signal_since or now) >= delay:
            st.autoscale_target = desired
            st._scale_signal_since = None
            st._scale_signal_dir = 0
            return True
        return False

    def _reconcile_loop(self) -> None:
        while not self._stop.wait(self._period):
            try:
                self._reconcile_once()
            except Exception:
                # The reconciler must never die; errors surface via health
                # checks and deploy() retries.
                pass
