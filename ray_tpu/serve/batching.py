"""@serve.batch — transparent request batching inside a replica.

ray: python/ray/serve/batching.py (the `@serve.batch` decorator).  The
reference's batcher is asyncio-based; replicas here execute requests on a
thread pool (one slot per concurrent query), so the batcher is thread-based:
the first caller into an empty batch becomes the leader, waits up to
batch_wait_timeout_s for the batch to fill to max_batch_size, runs the
wrapped function ONCE on the list of items, and distributes results.

This is the TPU serving hot path: batched JAX inference amortizes dispatch
and keeps the MXU fed with large matmuls instead of batch-1 GEMVs.
"""

from __future__ import annotations

import functools
import inspect
import threading
from typing import Any, Callable, List, Optional


class _Slot:
    __slots__ = ("item", "event", "result", "error")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _Batcher:
    def __init__(self, fn: Callable, max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._pending: List[_Slot] = []
        self._leader_active = False

    def submit(self, instance, item) -> Any:
        slot = _Slot(item)
        lead = False
        with self._lock:
            self._pending.append(slot)
            if not self._leader_active:
                self._leader_active = True
                lead = True
        if lead:
            self._run_leader(instance)
        # Leader completes its own slot synchronously; followers wait here.
        slot.event.wait()
        if slot.error is not None:
            raise slot.error
        return slot.result

    def _run_leader(self, instance):
        deadline = threading.Event()
        # Wait for the batch to fill or the window to expire.  Polling in
        # small slices keeps the window tight without a condition variable
        # per slot (the window is ~ms; precision beyond that doesn't matter).
        waited = 0.0
        step = min(0.002, self._timeout) if self._timeout > 0 else 0.0
        while waited < self._timeout:
            with self._lock:
                if len(self._pending) >= self._max:
                    break
            deadline.wait(step)
            waited += step
        with self._lock:
            batch, self._pending = self._pending[: self._max], self._pending[self._max :]
            # Hand leadership to the next waiter if items remain; they're
            # already blocked in submit() so a new leader must be crowned
            # here, not there.
            self._leader_active = bool(self._pending)
            relead = self._pending[0] if self._leader_active else None
        if relead is not None:
            threading.Thread(
                target=self._run_leader, args=(instance,), daemon=True
            ).start()
        items = [s.item for s in batch]
        try:
            out = self._fn(instance, items) if instance is not None else self._fn(items)
            if inspect.iscoroutine(out):
                import asyncio

                out = asyncio.run(out)
            if not isinstance(out, (list, tuple)) or len(out) != len(items):
                raise TypeError(
                    f"@serve.batch function must return a list of "
                    f"{len(items)} results (one per item), got {type(out)}"
                )
            for s, r in zip(batch, out):
                s.result = r
        except BaseException as e:  # noqa: BLE001 — every waiter must wake
            for s in batch:
                s.error = e
        finally:
            for s in batch:
                s.event.set()


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
):
    """Decorate a method taking a LIST of items and returning a LIST of
    results; callers invoke it with a SINGLE item and get a single result.

    ray: python/ray/serve/batching.py `@serve.batch`.
    """

    def deco(fn: Callable):
        batcher_attr = f"__serve_batcher_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, item):
            b = getattr(self, batcher_attr, None)
            if b is None:
                # Two concurrent first calls must share ONE batcher, or the
                # first batch window splits in half.  dict.setdefault is
                # atomic under the GIL — no lock (a closed-over Lock would
                # make decorated classes unpicklable for replica shipping);
                # the losing thread's _Batcher is garbage-collected unused.
                b = self.__dict__.setdefault(
                    batcher_attr, _Batcher(fn, max_batch_size, batch_wait_timeout_s)
                )
            return b.submit(self, item)

        wrapper._serve_batch_params = {
            "max_batch_size": max_batch_size,
            "batch_wait_timeout_s": batch_wait_timeout_s,
        }
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
