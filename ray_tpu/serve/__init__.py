"""ray_tpu.serve — model serving on the actor runtime.

ray: python/ray/serve/ — controller/replica reconciliation
(controller.py:64, _private/deployment_state.py:1812), router with
power-of-two-choices + max-in-flight (_private/router.py:221), HTTP proxy
(_private/http_proxy.py:234), @serve.batch batching (batching.py).

TPU-first design notes:
- replicas are plain actors whose callable jits once and then serves
  batched inference; @serve.batch keeps the MXU on large matmuls;
- the request path is ONE actor hop (router lives in the caller);
- the controller is a named actor running a reconcile loop — membership
  flows to routers via version-gated pulls, not per-request lookups.
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_deployment_handle,
    get_http_address,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from ray_tpu.serve.router import DeploymentHandle

__all__ = [
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "HTTPOptions",
    "batch",
    "delete",
    "deployment",
    "get_deployment_handle",
    "get_http_address",
    "run",
    "shutdown",
    "start",
    "status",
]
