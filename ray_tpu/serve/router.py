"""Router + DeploymentHandle: the request data plane.

ray: python/ray/serve/_private/router.py:221 (ReplicaSet.assign_replica —
power-of-two-choices with max-in-flight) and handle.py (DeploymentHandle).
The router lives in the CALLER's process (driver or HTTP proxy actor) and
talks straight to replica actors — the controller is only consulted to
refresh membership (version-gated pull, see controller.get_routing_table),
never per-request.  That keeps the request path one actor hop, the property
the reference's direct actor transport exists for (SURVEY §3.6).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu


class _StreamToken:
    """In-flight marker for a LIVE stream: a stream's first ref resolves
    immediately (stream_start returns a sid), so admission control tracks
    this token instead — it stays in-flight until the stream closes."""

    __slots__ = ("done",)

    def __init__(self):
        self.done = False


class _ReplicaSet:
    """Replica membership + local in-flight accounting for one deployment.

    Thread-safe on its OWN lock: assign() can block (backpressure) and
    must not hold the Router's lock while doing so — the long-poll push
    that adds capacity has to be able to land mid-wait."""

    def __init__(self, max_concurrent_queries: int):
        self._lock = threading.Lock()
        self.max_concurrent = max_concurrent_queries
        self.replicas: List[Tuple[str, Any]] = []  # (replica_id, handle)
        # replica_id -> outstanding refs + live-stream tokens
        self.inflight: Dict[str, List[Any]] = {}

    def update(self, replicas: List[Tuple[str, Any]], max_concurrent: int):
        with self._lock:
            self.replicas = list(replicas)
            self.max_concurrent = max_concurrent
            live = {rid for rid, _ in replicas}
            self.inflight = {
                rid: refs for rid, refs in self.inflight.items() if rid in live
            }

    _STALE_ENTRY_S = 120.0

    @staticmethod
    def _ref_state(ref):
        """Local-only readiness: True = definitively pending, False =
        definitively done, None = unknowable here.  The caller's direct
        transport knows the state of its own calls without ANY head
        traffic; a head wait here (the old implementation) put a hidden
        owner round trip on every assignment AND stalled the whole data
        plane for the reconnect window during a head outage — the proxy
        must keep serving while the head is down."""
        from ray_tpu._private.worker_proc import get_worker_runtime

        wr = get_worker_runtime()
        if wr is not None:
            if wr.direct is not None:
                r = wr.direct.ready_local(ref.id)
                if r is not None:
                    return not r  # owned: definitive either way
            # Relayed ref (e.g. the first calls before the direct route
            # resolved): the process still KNOWS completion once anything
            # here resolved the value (get_value marks known_materialized).
            if wr.known_materialized(ref.id):
                return False
            return None
        # Driver-side handle: the in-process runtime's store answers
        # readiness as a local dict check.
        from ray_tpu._private import runtime as rt_mod

        if rt_mod.is_initialized():
            return not rt_mod.get_runtime().store.is_ready(ref.id)
        return None

    def _purge_locked(self, rid: str):
        entries = self.inflight.get(rid)
        if not entries:
            return
        now = time.monotonic()
        keep = []
        for e, ts in entries:
            if isinstance(e, _StreamToken):
                if not e.done:
                    keep.append((e, ts))
                continue
            state = self._ref_state(e)
            if state is True:
                keep.append((e, ts))  # definitively pending: NEVER aged —
                # a 5-minute inference must keep counting against capacity
            elif state is None and now - ts < self._STALE_ENTRY_S:
                # Unknowable here (relayed, never resolved locally): age
                # out so it can't count against capacity forever.
                keep.append((e, ts))
        self.inflight[rid] = keep

    def record(self, rid: str, entry: Any) -> None:
        with self._lock:
            self.inflight.setdefault(rid, []).append((entry, time.monotonic()))

    def has_replicas(self) -> bool:
        with self._lock:
            return bool(self.replicas)

    def assign(self) -> Tuple[str, Any]:
        """Pick a replica: power-of-two-choices on local in-flight count
        (ray: router.py:221).  Blocks (with purging) while every replica is
        at max_concurrent — that's the handle-side backpressure."""
        deadline = time.time() + 60.0
        while True:
            with self._lock:
                if not self.replicas:
                    raise RuntimeError("no live replicas")
                if len(self.replicas) == 1:
                    cands = [self.replicas[0]]
                else:
                    cands = random.sample(self.replicas, 2)
                for rid, _h in cands:
                    self._purge_locked(rid)
                rid, h = min(
                    cands, key=lambda rh: len(self.inflight.get(rh[0], ()))
                )
                if len(self.inflight.get(rid, ())) < self.max_concurrent:
                    return rid, h
            if time.time() > deadline:
                raise TimeoutError(
                    "all replicas at max_concurrent_queries for 60s"
                )
            time.sleep(0.001)


class Router:
    """Per-process router over all deployments (ray: router.py Router).

    Membership arrives by PUSH: a background thread keeps one long-poll
    parked on the controller (ray: long_poll.py:185 LongPollClient), so a
    config/membership change reaches every router in push latency with
    zero per-request controller traffic."""

    def __init__(self, controller_handle, listen_chunk_s: float = 30.0):
        self._controller = controller_handle
        self._chunk = listen_chunk_s
        self._lock = threading.Lock()
        self._version = -1
        self._sets: Dict[str, _ReplicaSet] = {}
        self._stopped = False
        # Bootstrap table fetch is best-effort: a router built INSIDE a
        # replica's __init__ (graph ingress unpickling a child handle) must
        # not fail actor creation on a busy controller — the long-poll
        # listener below delivers the table moments later, and
        # assign_request force-pulls on a miss.
        try:
            self._refresh()
        except Exception:
            pass
        self._listen_thread = threading.Thread(
            target=self._listen_loop, daemon=True, name="serve-router-longpoll"
        )
        self._listen_thread.start()

    def _apply_table(self, out) -> None:
        if out is None:
            return
        with self._lock:
            if out["version"] <= self._version:
                return
            self._version = out["version"]
            live = set(out["table"].keys())
            for name, info in out["table"].items():
                rs = self._sets.get(name)
                if rs is None:
                    rs = self._sets[name] = _ReplicaSet(info["max_concurrent_queries"])
                rs.update(info["replicas"], info["max_concurrent_queries"])
            for name in list(self._sets.keys()):
                if name not in live:
                    del self._sets[name]

    def _refresh(self):
        out = ray_tpu.get(
            self._controller.get_routing_table.remote(self._version), timeout=10
        )
        self._apply_table(out)

    def stop(self) -> None:
        """Stop the long-poll listener (serve.shutdown): without this the
        daemon thread would hot-retry a dead controller forever."""
        self._stopped = True

    def _listen_loop(self) -> None:
        while not self._stopped:
            try:
                out = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._version, self._chunk
                    ),
                    timeout=self._chunk + 15,
                )
            except Exception:
                if self._stopped:
                    return
                time.sleep(0.5)  # controller restarting: retry
                continue
            self._apply_table(out)

    def assign_request(
        self, deployment: str, method_name: str, args: tuple, kwargs: dict,
        stream: bool = False, trace_parent=None,
    ):
        """Pick a replica and submit; returns the result ObjectRef (or a
        replica-sticky stream handle when stream=True).  Blocking
        backpressure happens on the replica set's OWN lock — the router
        lock is only held for map lookups, so the long-poll push can land
        while callers wait for capacity.

        trace_parent: the proxy's serve::request span context — the
        routing decision records as a child span, and the remote submit
        inside it stamps the spec's trace_ctx, so the replica's run span
        parents into the SAME request tree (one request id end to end)."""
        from ray_tpu.util import tracing

        with self._lock:
            rs = self._sets.get(deployment)
        if rs is None or not rs.has_replicas():
            # Push may still be in flight for a just-deployed app: force
            # one pull before failing.
            self._refresh()
            with self._lock:
                rs = self._sets.get(deployment)
            if rs is None or not rs.has_replicas():
                raise RuntimeError(f"deployment {deployment!r} has no replicas")
        with tracing.span(
            "serve::route", parent=trace_parent,
            attrs={"deployment": deployment},
        ):
            rid, handle = rs.assign()
            if stream:
                token = _StreamToken()
                sid_ref = handle.stream_start.remote(method_name, args, kwargs)
                rs.record(rid, token)  # live stream counts as in-flight
                return _StreamIterator(handle, sid_ref, token=token)
            ref = handle.handle_request.remote(method_name, args, kwargs)
            rs.record(rid, ref)
            return ref


class _StreamIterator:
    """Client side of a streaming call (ray: DeploymentResponseGenerator).

    Pulls item batches from the REPLICA that owns the generator (sticky —
    a generator cannot move between replicas).  Lazy: each __next__ fetches
    the next ready chunk, so the consumer sees early items while the
    replica is still producing later ones (token streaming)."""

    def __init__(self, replica_handle, sid_ref, batch: int = 1, token=None):
        self._h = replica_handle
        self._sid_ref = sid_ref
        self._sid = None
        self._batch = batch
        self._buf: List[Any] = []
        self._done = False
        self._token = token

    def __iter__(self):
        return self

    def _finish(self) -> None:
        self._done = True
        if self._token is not None:
            self._token.done = True  # release the router's in-flight slot

    def __next__(self):
        while not self._buf:
            if self._done:
                raise StopIteration
            try:
                if self._sid is None:
                    # Inside the try: a failed stream_start (bad method,
                    # replica death) must release the router's in-flight
                    # token, or the failed stream occupies a routing slot
                    # forever.
                    self._sid = ray_tpu.get(self._sid_ref, timeout=60)
                items, done = ray_tpu.get(
                    self._h.stream_next.remote(self._sid, self._batch), timeout=300
                )
            except Exception:
                self._finish()
                raise
            if done:
                self._finish()
            self._buf.extend(items)
        return self._buf.pop(0)

    def close(self) -> None:
        """Abandon the stream: tell the replica to drop the generator so
        it stops counting against its queue and frees captured state."""
        if self._done:
            return
        self._finish()
        try:
            if self._sid is None:
                self._sid = ray_tpu.get(self._sid_ref, timeout=10)
            self._h.stream_cancel.remote(self._sid)
        except Exception:
            pass  # replica already dead: nothing to cancel

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DeploymentHandle:
    """User-facing handle (ray: serve/handle.py DeploymentHandle).

    `h.remote(*a)` calls the deployment's __call__; `h.method.remote(*a)`
    calls a named method.  Results are ObjectRefs: ray_tpu.get() them, or
    `await` them inside async code (async handle API).
    `h.options(stream=True).remote(*a)` returns an iterator of the
    deployment generator's items (streaming responses)."""

    def __init__(
        self,
        deployment_name: str,
        router: Router,
        method_name: Optional[str] = None,
        stream: bool = False,
    ):
        self._name = deployment_name
        self._router = router
        self._method = method_name
        self._stream = stream

    def options(
        self, *, method_name: Optional[str] = None, stream: Optional[bool] = None
    ) -> "DeploymentHandle":
        return DeploymentHandle(
            self._name,
            self._router,
            method_name if method_name is not None else self._method,
            self._stream if stream is None else stream,
        )

    def remote(self, *args, **kwargs):
        return self._router.assign_request(
            self._name, self._method or "__call__", args, kwargs,
            stream=self._stream,
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._name, self._router, name, self._stream)

    def __reduce__(self):
        # Handles ship into OTHER processes (deployment-graph ingress
        # replicas hold child handles): rebuild there with a fresh Router
        # bound to the named controller — the local Router holds locks and
        # a live controller handle wrapper that don't pickle.
        return (_rebuild_handle, (self._name, self._method, self._stream))

    def __repr__(self):
        m = f".{self._method}" if self._method else ""
        return f"DeploymentHandle({self._name}{m})"


_process_router: Optional[Router] = None
_process_router_lock = threading.Lock()


def _rebuild_handle(
    name: str, method: Optional[str], stream: bool = False
) -> "DeploymentHandle":
    """ONE Router per process, shared by every unpickled handle: per-handle
    routers would each get their own in-flight accounting (N handles could
    push N x max_concurrent to one replica) and each poll the controller."""
    global _process_router
    with _process_router_lock:
        if _process_router is None:
            import ray_tpu
            from ray_tpu.serve.config import SERVE_CONTROLLER_NAME, SERVE_NAMESPACE

            controller = ray_tpu.get_actor(SERVE_CONTROLLER_NAME, SERVE_NAMESPACE)
            _process_router = Router(controller)
    return DeploymentHandle(name, _process_router, method, stream)
