"""Router + DeploymentHandle: the request data plane.

ray: python/ray/serve/_private/router.py:221 (ReplicaSet.assign_replica —
power-of-two-choices with max-in-flight) and handle.py (DeploymentHandle).
The router lives in the CALLER's process (driver or HTTP proxy actor) and
talks straight to replica actors — the controller is only consulted to
refresh membership (version-gated pull, see controller.get_routing_table),
never per-request.  That keeps the request path one actor hop, the property
the reference's direct actor transport exists for (SURVEY §3.6).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu


class _ReplicaSet:
    """Replica membership + local in-flight accounting for one deployment."""

    def __init__(self, max_concurrent_queries: int):
        self.max_concurrent = max_concurrent_queries
        self.replicas: List[Tuple[str, Any]] = []  # (replica_id, handle)
        self.inflight: Dict[str, List[Any]] = {}  # replica_id -> outstanding refs

    def update(self, replicas: List[Tuple[str, Any]], max_concurrent: int):
        self.replicas = list(replicas)
        self.max_concurrent = max_concurrent
        live = {rid for rid, _ in replicas}
        self.inflight = {rid: refs for rid, refs in self.inflight.items() if rid in live}

    def _purge(self, rid: str):
        refs = self.inflight.get(rid)
        if not refs:
            return
        done, pending = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        self.inflight[rid] = pending

    def assign(self) -> Tuple[str, Any]:
        """Pick a replica: power-of-two-choices on local in-flight count
        (ray: router.py:221).  Blocks (with purging) while every replica is
        at max_concurrent — that's the handle-side backpressure."""
        if not self.replicas:
            raise RuntimeError("no live replicas")
        deadline = time.time() + 60.0
        while True:
            if len(self.replicas) == 1:
                cands = [self.replicas[0]]
            else:
                cands = random.sample(self.replicas, 2)
            for rid, _h in cands:
                self._purge(rid)
            rid, h = min(cands, key=lambda rh: len(self.inflight.get(rh[0], ())))
            if len(self.inflight.get(rid, ())) < self.max_concurrent:
                return rid, h
            if time.time() > deadline:
                raise TimeoutError(
                    "all replicas at max_concurrent_queries for 60s"
                )
            time.sleep(0.001)


class Router:
    """Per-process router over all deployments (ray: router.py Router)."""

    def __init__(self, controller_handle, refresh_interval_s: float = 0.25):
        self._controller = controller_handle
        self._interval = refresh_interval_s
        self._lock = threading.Lock()
        self._version = -1
        self._last_refresh = 0.0
        self._sets: Dict[str, _ReplicaSet] = {}

    def _refresh(self, force: bool = False):
        now = time.time()
        if not force and now - self._last_refresh < self._interval:
            return
        self._last_refresh = now
        out = ray_tpu.get(
            self._controller.get_routing_table.remote(self._version), timeout=10
        )
        if out is None:
            return
        self._version = out["version"]
        live = set(out["table"].keys())
        for name, info in out["table"].items():
            rs = self._sets.get(name)
            if rs is None:
                rs = self._sets[name] = _ReplicaSet(info["max_concurrent_queries"])
            rs.update(info["replicas"], info["max_concurrent_queries"])
        for name in list(self._sets.keys()):
            if name not in live:
                del self._sets[name]

    def assign_request(
        self, deployment: str, method_name: str, args: tuple, kwargs: dict
    ):
        """Pick a replica and submit; returns the result ObjectRef."""
        with self._lock:
            self._refresh()
            rs = self._sets.get(deployment)
            if rs is None or not rs.replicas:
                # Maybe stale: force one refresh before failing.
                self._refresh(force=True)
                rs = self._sets.get(deployment)
                if rs is None or not rs.replicas:
                    raise RuntimeError(f"deployment {deployment!r} has no replicas")
            rid, handle = rs.assign()
            ref = handle.handle_request.remote(method_name, args, kwargs)
            rs.inflight.setdefault(rid, []).append(ref)
            return ref


class DeploymentHandle:
    """User-facing handle (ray: serve/handle.py DeploymentHandle).

    `h.remote(*a)` calls the deployment's __call__; `h.method.remote(*a)`
    calls a named method.  Results are ObjectRefs: ray_tpu.get() them."""

    def __init__(self, deployment_name: str, router: Router, method_name: Optional[str] = None):
        self._name = deployment_name
        self._router = router
        self._method = method_name

    def options(self, *, method_name: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(self._name, self._router, method_name)

    def remote(self, *args, **kwargs):
        return self._router.assign_request(
            self._name, self._method or "__call__", args, kwargs
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._name, self._router, name)

    def __reduce__(self):
        # Handles ship into OTHER processes (deployment-graph ingress
        # replicas hold child handles): rebuild there with a fresh Router
        # bound to the named controller — the local Router holds locks and
        # a live controller handle wrapper that don't pickle.
        return (_rebuild_handle, (self._name, self._method))

    def __repr__(self):
        m = f".{self._method}" if self._method else ""
        return f"DeploymentHandle({self._name}{m})"


_process_router: Optional[Router] = None
_process_router_lock = threading.Lock()


def _rebuild_handle(name: str, method: Optional[str]) -> "DeploymentHandle":
    """ONE Router per process, shared by every unpickled handle: per-handle
    routers would each get their own in-flight accounting (N handles could
    push N x max_concurrent to one replica) and each poll the controller."""
    global _process_router
    with _process_router_lock:
        if _process_router is None:
            import ray_tpu
            from ray_tpu.serve.config import SERVE_CONTROLLER_NAME, SERVE_NAMESPACE

            controller = ray_tpu.get_actor(SERVE_CONTROLLER_NAME, SERVE_NAMESPACE)
            _process_router = Router(controller)
    return DeploymentHandle(name, _process_router, method)
