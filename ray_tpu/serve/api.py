"""Public Serve API.

ray: python/ray/serve/api.py (serve.run :458, @serve.deployment :254,
serve.start, serve.shutdown, serve.get_deployment_handle).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Union

import cloudpickle

import ray_tpu
from ray_tpu.serve.config import (
    SERVE_CONTROLLER_NAME,
    SERVE_NAMESPACE,
    AutoscalingConfig,
    DeploymentConfig,
    HTTPOptions,
)
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.http_proxy import HTTPProxy
from ray_tpu.serve.router import DeploymentHandle, Router

_lock = threading.Lock()
_controller = None  # ActorHandle
_proxy = None  # ActorHandle
_router: Optional[Router] = None


def start(
    http_options: Optional[Union[HTTPOptions, dict]] = None,
    detached: bool = True,
) -> None:
    """Start (or connect to) the Serve controller; optionally an HTTP proxy.

    ray: serve.start — one controller per cluster, found by name."""
    global _controller, _proxy, _router
    ray_tpu.init(ignore_reinit_error=True)
    with _lock:
        if _controller is None:
            _controller = (
                ray_tpu.remote(ServeController)
                .options(
                    name=SERVE_CONTROLLER_NAME,
                    namespace=SERVE_NAMESPACE,
                    get_if_exists=True,
                    # Every router (driver, proxy, each graph replica)
                    # parks one long-poll listener on a concurrency slot;
                    # leave generous headroom for control calls.
                    max_concurrency=64,
                    # A crash-killed controller restarts instead of taking
                    # the control plane down with it (ray: the serve
                    # controller is detached + supervised the same way).
                    # Deployment state is re-declared by the next deploy();
                    # live replicas keep serving through the router tables
                    # the proxy already holds.
                    max_restarts=-1,
                )
                .remote()
            )
            ray_tpu.get(_controller.ping.remote(), timeout=30)
            _router = Router(_controller)
        if http_options is not None and _proxy is None:
            if isinstance(http_options, dict):
                http_options = HTTPOptions(**http_options)
            _proxy = (
                ray_tpu.remote(HTTPProxy)
                # max_restarts: a crash-killed proxy rebinds and serves
                # again (PR 1 soak gap (c): it used to stay dead).  The
                # restarted instance re-runs __init__ with the original
                # creation args — controller handle included — and
                # re-learns the routing table from the live controller.
                .options(max_concurrency=32, max_restarts=-1)
                .remote(
                    _controller, http_options.host, http_options.port,
                    http_options.max_connections,
                )
            )
            ray_tpu.get(_proxy.ping.remote(), timeout=30)


def _ensure_started():
    if _controller is None:
        start()


class Application:
    """A deployment bound to its init args (ray: serve 2.x Application —
    the object `serve.run` accepts).

    Init args may contain OTHER Applications (deployment graphs,
    ray: serve/deployment_graph_build.py): `serve.run` deploys children
    first and the parent receives their DeploymentHandles — the ingress
    fans out to downstream deployments over plain handle calls."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs

    def _resolve(self, _deployed: Optional[Dict[int, "DeploymentHandle"]] = None) -> "DeploymentHandle":
        """Deploy this node's children (depth-first, each once — the memo
        threads through the WHOLE graph so a diamond-shared child deploys
        a single time), then this deployment with child handles substituted
        into its init args."""
        deployed = _deployed if _deployed is not None else {}

        def subst(value):
            if isinstance(value, Application):
                if id(value) not in deployed:
                    deployed[id(value)] = value._resolve(deployed)
                return deployed[id(value)]
            if isinstance(value, list):
                return [subst(v) for v in value]
            if isinstance(value, tuple):
                return tuple(subst(v) for v in value)
            if isinstance(value, dict):
                return {k: subst(v) for k, v in value.items()}
            return value

        args = tuple(subst(a) for a in self.init_args)
        kwargs = {k: subst(v) for k, v in self.init_kwargs.items()}
        return self.deployment.deploy(*args, **kwargs)


class Deployment:
    """Result of @serve.deployment (ray: serve/deployment.py Deployment)."""

    def __init__(self, target: Union[type, Callable], name: str, config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config

    def options(self, **opts) -> "Deployment":
        cfg = self.config.to_dict()
        name = opts.pop("name", self.name)
        for k, v in opts.items():
            if k not in cfg:
                raise TypeError(f"unknown deployment option {k!r}")
            cfg[k] = v
        return Deployment(self._target, name, DeploymentConfig.from_dict(cfg))

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def deploy(self, *init_args, **init_kwargs) -> DeploymentHandle:
        _ensure_started()
        blob = cloudpickle.dumps(self._target)
        ray_tpu.get(
            _controller.deploy.remote(
                self.name, blob, init_args, init_kwargs, self.config.to_dict()
            ),
            timeout=60,
        )
        ray_tpu.get(
            _controller.wait_for_ready.remote(self.name, 60.0), timeout=70
        )
        return DeploymentHandle(self.name, _router)

    def __call__(self, *a, **kw):
        raise TypeError(
            "deployments are not directly callable; use .deploy() + handle.remote()"
        )


def deployment(
    _target: Optional[Union[type, Callable]] = None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    max_concurrent_queries: int = 8,
    user_config: Any = None,
    autoscaling_config: Optional[Union[AutoscalingConfig, dict]] = None,
    health_check_period_s: float = 0.25,
    health_check_timeout_s: float = 10.0,
    graceful_shutdown_timeout_s: float = 5.0,
    ray_actor_options: Optional[Dict[str, Any]] = None,
):
    """@serve.deployment decorator (ray: serve/api.py:254)."""

    def deco(target):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            user_config=user_config,
            autoscaling_config=autoscaling_config,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            ray_actor_options=ray_actor_options or {},
        )
        return Deployment(target, name or target.__name__, cfg)

    if _target is not None:
        return deco(_target)
    return deco


def run(app: Union[Application, Deployment], **kwargs) -> DeploymentHandle:
    """Deploy an application — including any deployment GRAPH bound into
    its init args — and return the ingress handle (ray: serve.run :458)."""
    if isinstance(app, Deployment):
        app = app.bind()
    return app._resolve()


def get_deployment_handle(name: str) -> DeploymentHandle:
    _ensure_started()
    return DeploymentHandle(name, _router)


def get_http_address() -> Optional[str]:
    if _proxy is None:
        return None
    return ray_tpu.get(_proxy.address.remote(), timeout=10)


def status() -> Dict[str, Any]:
    _ensure_started()
    return ray_tpu.get(_controller.list_deployments.remote(), timeout=10)


def delete(name: str) -> None:
    _ensure_started()
    ray_tpu.get(_controller.delete_deployment.remote(name), timeout=30)


def shutdown() -> None:
    """Tear down all deployments + the controller/proxy."""
    global _controller, _proxy, _router
    with _lock:
        if _controller is not None:
            try:
                ray_tpu.get(_controller.shutdown.remote(), timeout=30)
                ray_tpu.kill(_controller)
            except Exception:
                pass
        if _proxy is not None:
            try:
                ray_tpu.get(_proxy.shutdown.remote(), timeout=10)
                ray_tpu.kill(_proxy)
            except Exception:
                pass
        if _router is not None:
            _router.stop()
        from ray_tpu.serve import router as _router_mod

        with _router_mod._process_router_lock:
            if _router_mod._process_router is not None:
                _router_mod._process_router.stop()
                _router_mod._process_router = None
        _controller = None
        _proxy = None
        _router = None
