"""Replica: the actor that hosts one copy of a deployment's user callable.

ray: python/ray/serve/_private/replica.py:57 (RayServeReplica;
handle_request :507).  The replica actor runs with
max_concurrency = max_concurrent_queries + control slots, so health checks
and metrics answer even while every query slot is busy — the same reason the
reference separates its control-plane concurrency group.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Dict, Optional

import cloudpickle

_STREAM_END = object()


class Replica:
    """Actor payload.  Instantiated by the controller via
    `ray_tpu.remote(Replica).options(...).remote(...)`."""

    def __init__(
        self,
        deployment_name: str,
        replica_id: str,
        callable_blob: bytes,
        init_args: tuple,
        init_kwargs: dict,
        user_config: Any = None,
    ):
        self._deployment_name = deployment_name
        self._replica_id = replica_id
        target = cloudpickle.loads(callable_blob)
        if inspect.isclass(target):
            self._callable = target(*init_args, **(init_kwargs or {}))
            self._is_function = False
        else:
            self._callable = target
            self._is_function = True
        self._lock = threading.Lock()
        self._ongoing = 0
        self._processed = 0
        self._start_time = time.time()
        self._streams: Dict[str, Any] = {}  # stream_id -> live generator
        self._stream_counter = 0
        # Replica telemetry (ray: serve's autoscaling_metrics push): queue
        # depth + request latency recorded into this process's registry,
        # shipped to the head by the worker's generic metric push — the
        # measurement ROADMAP item 3's autoscaler consumes.
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        tags = {"deployment": deployment_name, "replica": replica_id}
        self._m_queue = Gauge(
            "serve_replica_queue_depth",
            "in-flight requests on this replica",
            tag_keys=("deployment", "replica"),
        ).set_default_tags(tags)
        self._m_latency = Histogram(
            "serve_replica_request_latency_s",
            "request handling latency",
            boundaries=[0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0],
            tag_keys=("deployment", "replica"),
        ).set_default_tags(tags)
        self._m_requests = Counter(
            "serve_replica_requests",
            "requests processed (by outcome)",
            tag_keys=("deployment", "replica", "outcome"),
        ).set_default_tags(tags)
        if user_config is not None:
            self.reconfigure(user_config)

    # -- data plane -------------------------------------------------------
    def handle_request(self, method_name: str, args: tuple, kwargs: dict):
        """Execute one request.  Called concurrently from the actor's
        thread pool (one slot per in-flight query)."""
        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        outcome = "error"
        with self._lock:
            self._ongoing += 1
            self._m_queue.set(self._ongoing)
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name or "__call__")
            # The request span tree's leaf: parents to the ambient
            # run::handle_request span, which carries the proxy's trace
            # id via the spec's trace_ctx — one parented tree per serve
            # request in the merged timeline.
            with tracing.span(
                "serve::replica",
                attrs={
                    "deployment": self._deployment_name,
                    "replica": self._replica_id,
                },
            ):
                out = fn(*args, **(kwargs or {}))
                if inspect.iscoroutine(out):
                    import asyncio

                    out = asyncio.run(out)
            outcome = "ok"
            return out
        finally:
            with self._lock:
                self._ongoing -= 1
                self._processed += 1
                self._m_queue.set(self._ongoing)
            self._m_latency.observe(time.perf_counter() - t0)
            self._m_requests.inc(tags={"outcome": outcome})

    # -- streaming data plane (ray: replica.py handle_request_streaming /
    #    ObjectRefGenerator semantics, pulled replica-side) ----------------
    def stream_start(self, method_name: str, args: tuple, kwargs: dict) -> str:
        """Begin a streaming call: the user method must return a generator
        (e.g. an LM decode loop yielding tokens).  Returns a stream id the
        caller pulls with stream_next — sticky to THIS replica."""
        import inspect as _inspect

        if self._is_function:
            fn = self._callable
        else:
            fn = getattr(self._callable, method_name or "__call__")
        gen = fn(*args, **(kwargs or {}))
        if not (_inspect.isgenerator(gen) or hasattr(gen, "__next__")):
            gen = iter([gen])  # non-generator result: one-item stream
        with self._lock:
            self._stream_counter += 1
            sid = f"s{self._stream_counter}"
            self._streams[sid] = gen
            self._ongoing += 1  # a live stream occupies queue capacity
        return sid

    def stream_next(self, sid: str, max_items: int = 1):
        """Pull the next item from the stream.  Returns (items, done).
        One item per call: a sync generator has no "ready" notion, so
        pulling more would block on FUTURE items and destroy
        time-to-first-token — the per-token RPC is the price of streaming
        over a sync generator (the reference streams per-item over its
        generator refs for the same reason)."""
        gen = self._streams.get(sid)
        if gen is None:
            return [], True
        try:
            item = next(gen)
        except StopIteration:
            self._close_stream(sid)
            return [], True
        except Exception:
            self._close_stream(sid)
            raise
        return [item], False

    def stream_cancel(self, sid: str) -> None:
        """Client abandoned the stream (disconnect / GC'd iterator): drop
        the generator so its captured state frees and it stops counting as
        an ongoing query."""
        gen = self._streams.get(sid)
        if gen is not None and hasattr(gen, "close"):
            try:
                gen.close()  # runs the generator's finally blocks
            except Exception:
                pass
        self._close_stream(sid)

    def _close_stream(self, sid: str) -> None:
        with self._lock:
            if self._streams.pop(sid, None) is not None:
                self._ongoing -= 1
                self._processed += 1

    # -- control plane ----------------------------------------------------
    def reconfigure(self, user_config: Any) -> None:
        """ray: replica.py reconfigure — forwarded to the user callable's
        `reconfigure` method when it defines one."""
        if not self._is_function and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    def check_health(self) -> Dict[str, Any]:
        """Liveness + the queue metric the autoscaler consumes
        (ray: _private/autoscaling_metrics.py pushes; we pull on the same
        health-check RPC to halve control traffic)."""
        if not self._is_function and hasattr(self._callable, "check_health"):
            # User-defined health check: raising marks the replica unhealthy.
            self._callable.check_health()
        with self._lock:
            return {
                "replica_id": self._replica_id,
                "ongoing": self._ongoing,
                "processed": self._processed,
                "uptime_s": time.time() - self._start_time,
            }

    def prepare_for_shutdown(self, timeout_s: float = 5.0) -> bool:
        """Drain: wait for in-flight queries to finish before the controller
        kills the actor (ray: replica.py graceful shutdown)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return True
            time.sleep(0.02)
        return False
