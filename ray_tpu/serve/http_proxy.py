"""HTTP proxy: asyncio HTTP/1.1 server inside an actor.

ray: python/ray/serve/_private/http_proxy.py:234,415 (HTTPProxy/
HTTPProxyActor — an asyncio/uvicorn event loop, NOT a thread per
connection).  Rounds 1-3 used ThreadingHTTPServer: fine at benchmark QPS,
but a thread per keep-alive connection cannot hold thousands of idle
clients.  This build speaks HTTP/1.1 over asyncio streams with no external
deps:

  * idle keep-alive connections cost one coroutine each, bounded by the
    serve_proxy_max_connections knob (excess connections are refused at
    accept instead of silently degrading everyone);
  * active requests resolve replica responses on a bounded thread pool
    (serve_proxy_threads) — the router's replica calls ride the direct
    worker-to-worker transport (peer.py), so a request never touches the
    head on the hot path;
  * streaming responses are chunked NDJSON, one line per generator item,
    flushed as produced (ray: serve StreamingResponse over ASGI).

Routing: POST/GET /<deployment-name> with a JSON body (or query string) →
Router.assign_request → JSON response.
"""

from __future__ import annotations

import asyncio
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import ray_tpu
from ray_tpu._private import config as _config
from ray_tpu.serve.router import Router

_MAX_HEADER_BYTES = 64 * 1024
_IDLE_TIMEOUT_S = 120.0
_STREAM_END = object()


class _BadRequest(Exception):
    pass


async def _read_request(reader) -> Optional[Tuple[str, str, dict, bytes]]:
    """Parse one HTTP/1.1 request; None = clean EOF (client closed)."""
    try:
        line = await asyncio.wait_for(reader.readline(), _IDLE_TIMEOUT_S)
    except asyncio.TimeoutError:
        return None  # idle keep-alive expired
    if not line:
        return None
    parts = line.decode("latin-1").rstrip("\r\n").split(" ")
    if len(parts) != 3:
        raise _BadRequest("malformed request line")
    method, target, _version = parts
    headers = {}
    total = len(line)
    while True:
        h = await asyncio.wait_for(reader.readline(), _IDLE_TIMEOUT_S)
        total += len(h)
        if total > _MAX_HEADER_BYTES:
            raise _BadRequest("headers too large")
        if h in (b"\r\n", b"\n", b""):
            break
        k, _, v = h.decode("latin-1").partition(":")
        headers[k.strip().lower()] = v.strip()
    n = int(headers.get("content-length", 0) or 0)
    # Body read carries the same deadline as the headers: a client that
    # declares a Content-Length and withholds bytes must not pin a
    # connection slot forever.
    body = (
        await asyncio.wait_for(reader.readexactly(n), _IDLE_TIMEOUT_S)
        if n else b""
    )
    return method, target, headers, body


def _json_response(code: int, payload, keep_alive: bool,
                   request_id: str = "") -> bytes:
    try:
        data = json.dumps(payload).encode()
    except TypeError:
        data = json.dumps({"result": repr(payload)}).encode()
    reason = {200: "OK", 404: "Not Found", 500: "Internal Server Error",
              503: "Service Unavailable"}.get(code, "OK")
    rid_header = f"X-Request-Id: {request_id}\r\n" if request_id else ""
    head = (
        f"HTTP/1.1 {code} {reason}\r\n"
        f"Content-Type: application/json\r\n"
        f"Content-Length: {len(data)}\r\n"
        f"{rid_header}"
        f"Connection: {'keep-alive' if keep_alive else 'close'}\r\n\r\n"
    ).encode("latin-1")
    return head + data


def _next_item(it):
    """Executor-side step of a blocking stream iterator."""
    try:
        return it.__next__()
    except StopIteration:
        return _STREAM_END


class HTTPProxy:
    """Actor payload: owns the asyncio loop thread + a Router."""

    def __init__(self, controller_handle, host: str = "127.0.0.1", port: int = 0,
                 max_connections: int = 0):
        self._router = Router(controller_handle)
        self._host = host
        self._pool = ThreadPoolExecutor(
            max_workers=_config.get("serve_proxy_threads"),
            thread_name_prefix="serve-resolve",
        )
        self._max_conns = max_connections or _config.get(
            "serve_proxy_max_connections"
        )
        self._open_conns = 0
        self._loop = asyncio.new_event_loop()
        started = threading.Event()
        boot: dict = {}

        def _run_loop():
            asyncio.set_event_loop(self._loop)

            async def _boot():
                server = await asyncio.start_server(
                    self._handle_conn, host, port, backlog=512
                )
                boot["server"] = server
                boot["port"] = server.sockets[0].getsockname()[1]

            try:
                self._loop.run_until_complete(_boot())
            except BaseException as e:  # noqa: BLE001 — surfaced below
                boot["error"] = e
                started.set()
                return
            started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(
            target=_run_loop, daemon=True, name="serve-http"
        )
        self._thread.start()
        if not started.wait(30):
            raise RuntimeError("serve HTTP proxy failed to start within 30s")
        if "error" in boot:
            # Bind failure (port in use, perms) must fail actor creation
            # loudly, exactly like the threaded server's constructor did.
            raise boot["error"]
        self._server = boot["server"]
        self._port = boot["port"]

    # -- connection handling -------------------------------------------------

    async def _handle_conn(self, reader, writer) -> None:
        if self._open_conns >= self._max_conns:
            # Bounded keep-alive: refuse loudly instead of degrading every
            # existing connection (ray: uvicorn limit-concurrency 503s).
            try:
                writer.write(_json_response(503, {"error": "too many connections"}, False))
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            writer.close()
            return
        self._open_conns += 1
        try:
            sock = writer.get_extra_info("socket")
            if sock is not None:
                import socket as _socket

                sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            while True:
                try:
                    req = await _read_request(reader)
                except (_BadRequest, ValueError):
                    writer.write(_json_response(400, {"error": "bad request"}, False))
                    await writer.drain()
                    return
                except (asyncio.IncompleteReadError, ConnectionError, OSError):
                    return
                if req is None:
                    return
                method, target, headers, body = req
                keep = headers.get("connection", "keep-alive").lower() != "close"
                try:
                    done = await self._dispatch(writer, method, target, body, keep)
                except (ConnectionError, OSError):
                    return
                if not done or not keep:
                    return
        finally:
            self._open_conns -= 1
            try:
                writer.close()
            except OSError:
                pass

    async def _dispatch(self, writer, method: str, target: str, raw: bytes,
                        keep: bool) -> bool:
        """Route one request; returns False when the connection must close
        (e.g. a broken stream).  Replica resolution is blocking router
        code, so it runs on the bounded executor pool."""
        path = urlparse(target)
        deployment = path.path.strip("/").split("/")[0]
        if not deployment:
            writer.write(_json_response(404, {"error": "no deployment in path"}, keep))
            await writer.drain()
            return True
        q = {k: v[0] for k, v in parse_qs(path.query).items()}
        stream = q.pop("stream", "0") in ("1", "true")
        body: Any = None
        if raw:
            try:
                body = json.loads(raw)
            except Exception:
                body = raw.decode(errors="replace")
        if body is None and q:
            body = q
        args = (body,) if body is not None else ()
        loop = asyncio.get_running_loop()
        if stream:
            return await self._stream_reply(writer, loop, deployment, args)
        # Request tracing (ROADMAP item 3's p99 debugging leg): ONE
        # request id — the trace id — spans proxy → router → replica, so
        # the merged timeline renders each serve request as a single
        # parented span tree.  The span's context is passed EXPLICITLY to
        # the executor-pool resolve (contextvars don't cross
        # run_in_executor), and the id returns as X-Request-Id.
        from ray_tpu.util import tracing

        span_cm = ctx = None
        if tracing.is_enabled():
            span_cm = tracing.span(
                "serve::request",
                attrs={"deployment": deployment, "method": method},
            )
            ctx = span_cm.__enter__()
        rid = (ctx or {}).get("trace_id", "")
        try:
            out = await loop.run_in_executor(
                self._pool, self._resolve, deployment, args, ctx
            )
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            writer.write(
                _json_response(500, {"error": str(e)}, keep, request_id=rid)
            )
            await writer.drain()
            return True
        finally:
            if span_cm is not None:
                span_cm.__exit__(None, None, None)
        writer.write(
            _json_response(200, {"result": out}, keep, request_id=rid)
        )
        await writer.drain()
        return True

    def _resolve(self, deployment: str, args: tuple, trace_parent=None):
        ref = self._router.assign_request(
            deployment, "__call__", args, {}, trace_parent=trace_parent
        )
        return ray_tpu.get(ref, timeout=60)

    async def _stream_reply(self, writer, loop, deployment: str, args: tuple) -> bool:
        """Chunked NDJSON: one line per generator item.  Never raises past
        the headers: once they go out, an error MUST be framed as a final
        chunk — a second HTTP response inside the chunked body would
        corrupt it."""
        try:
            it = await loop.run_in_executor(
                self._pool,
                lambda: self._router.assign_request(
                    deployment, "__call__", args, {}, stream=True
                ),
            )
        except Exception as e:  # noqa: BLE001 — pre-headers: plain 500
            writer.write(_json_response(500, {"error": str(e)}, True))
            await writer.drain()
            return True
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: application/x-ndjson\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
        )
        try:
            while True:
                try:
                    item = await loop.run_in_executor(self._pool, _next_item, it)
                except Exception as e:  # noqa: BLE001 — mid-stream error
                    data = (json.dumps({"error": str(e)}) + "\n").encode()
                    writer.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
                    break
                if item is _STREAM_END:
                    break
                data = (json.dumps({"item": item}) + "\n").encode()
                writer.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
                await writer.drain()
            writer.write(b"0\r\n\r\n")
            await writer.drain()
            return True
        except (ConnectionError, OSError):
            it.close()  # client hung up: release the replica stream
            return False

    # -- actor surface -------------------------------------------------------

    def address(self) -> str:
        return f"http://{self._host}:{self._port}"

    def port(self) -> int:
        return self._port

    def ping(self) -> str:
        return "pong"

    def open_connections(self) -> int:
        return self._open_conns

    def shutdown(self) -> None:
        def _stop():
            if self._server is not None:
                self._server.close()
            self._loop.stop()

        try:
            self._loop.call_soon_threadsafe(_stop)
        except RuntimeError:
            pass
        self._pool.shutdown(wait=False)
