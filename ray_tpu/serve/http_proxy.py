"""HTTP proxy: stdlib threaded HTTP server inside an actor.

ray: python/ray/serve/_private/http_proxy.py:234,415 (HTTPProxy/
HTTPProxyActor, uvicorn-based).  This build uses ThreadingHTTPServer — no
external deps, good enough for the controller-plane QPS the tests measure;
the heavy lifting (batched JAX inference) happens in replicas, and each
proxy request thread blocks only on its own ray_tpu.get.

Routing: POST/GET /<deployment-name> with a JSON body (or query string) →
Router.assign_request → JSON response.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

import ray_tpu
from ray_tpu.serve.router import Router


class HTTPProxy:
    """Actor payload: owns the server thread + a Router."""

    def __init__(self, controller_handle, host: str = "127.0.0.1", port: int = 0):
        self._router = Router(controller_handle)
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: required for chunked transfer (streaming responses);
            # non-streaming replies all carry Content-Length.
            protocol_version = "HTTP/1.1"
            # Headers and body go out as separate small writes: without
            # TCP_NODELAY, Nagle holds the second segment for the peer's
            # delayed ACK — measured ~40ms p50 on keep-alive connections.
            disable_nagle_algorithm = True

            def log_message(self, *a):  # quiet
                pass

            def _dispatch(self, body: Any):
                path = urlparse(self.path)
                deployment = path.path.strip("/").split("/")[0]
                if not deployment:
                    self._reply(404, {"error": "no deployment in path"})
                    return
                q = {k: v[0] for k, v in parse_qs(path.query).items()}
                stream = q.pop("stream", "0") in ("1", "true")
                if body is None and q:
                    body = q
                try:
                    args = (body,) if body is not None else ()
                    if stream:
                        self._stream_reply(deployment, args)
                        return
                    ref = proxy._router.assign_request(
                        deployment, "__call__", args, {}
                    )
                    out = ray_tpu.get(ref, timeout=60)
                    self._reply(200, {"result": out})
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    self._reply(500, {"error": str(e)})

            def _stream_reply(self, deployment: str, args: tuple):
                """Chunked NDJSON: one line per generator item, flushed as
                produced — the client reads tokens while the replica is
                still decoding (ray: serve streaming responses /
                StreamingResponse over ASGI).  Never raises: once headers
                go out, an error MUST be framed as a final chunk — a second
                HTTP response inside the chunked body would corrupt it."""
                try:
                    it = proxy._router.assign_request(
                        deployment, "__call__", args, {}, stream=True
                    )
                except Exception as e:  # noqa: BLE001 — pre-headers: plain 500
                    self._reply(500, {"error": str(e)})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def _chunk(payload: dict) -> None:
                    data = (json.dumps(payload) + "\n").encode()
                    self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
                    self.wfile.flush()

                try:
                    try:
                        for item in it:
                            _chunk({"item": item})
                    except (BrokenPipeError, ConnectionResetError):
                        raise
                    except Exception as e:  # noqa: BLE001 — mid-stream error
                        _chunk({"error": str(e)})
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    it.close()  # client hung up: release the replica stream

            def _reply(self, code: int, payload):
                try:
                    data = json.dumps(payload).encode()
                except TypeError:
                    data = json.dumps({"result": repr(payload)}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._dispatch(None)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                body = None
                if raw:
                    try:
                        body = json.loads(raw)
                    except Exception:
                        body = raw.decode(errors="replace")
                self._dispatch(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._port = self._server.server_address[1]
        self._host = host
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="serve-http"
        )
        self._thread.start()

    def address(self) -> str:
        return f"http://{self._host}:{self._port}"

    def port(self) -> int:
        return self._port

    def ping(self) -> str:
        return "pong"

    def shutdown(self) -> None:
        self._server.shutdown()
