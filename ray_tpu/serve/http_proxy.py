"""HTTP proxy: stdlib threaded HTTP server inside an actor.

ray: python/ray/serve/_private/http_proxy.py:234,415 (HTTPProxy/
HTTPProxyActor, uvicorn-based).  This build uses ThreadingHTTPServer — no
external deps, good enough for the controller-plane QPS the tests measure;
the heavy lifting (batched JAX inference) happens in replicas, and each
proxy request thread blocks only on its own ray_tpu.get.

Routing: POST/GET /<deployment-name> with a JSON body (or query string) →
Router.assign_request → JSON response.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

import ray_tpu
from ray_tpu.serve.router import Router


class HTTPProxy:
    """Actor payload: owns the server thread + a Router."""

    def __init__(self, controller_handle, host: str = "127.0.0.1", port: int = 0):
        self._router = Router(controller_handle)
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _dispatch(self, body: Any):
                path = urlparse(self.path)
                deployment = path.path.strip("/").split("/")[0]
                if not deployment:
                    self._reply(404, {"error": "no deployment in path"})
                    return
                if body is None and path.query:
                    q = {k: v[0] for k, v in parse_qs(path.query).items()}
                    body = q or None
                try:
                    args = (body,) if body is not None else ()
                    ref = proxy._router.assign_request(
                        deployment, "__call__", args, {}
                    )
                    out = ray_tpu.get(ref, timeout=60)
                    self._reply(200, {"result": out})
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    self._reply(500, {"error": str(e)})

            def _reply(self, code: int, payload):
                try:
                    data = json.dumps(payload).encode()
                except TypeError:
                    data = json.dumps({"result": repr(payload)}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._dispatch(None)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                body = None
                if raw:
                    try:
                        body = json.loads(raw)
                    except Exception:
                        body = raw.decode(errors="replace")
                self._dispatch(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._port = self._server.server_address[1]
        self._host = host
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="serve-http"
        )
        self._thread.start()

    def address(self) -> str:
        return f"http://{self._host}:{self._port}"

    def port(self) -> int:
        return self._port

    def ping(self) -> str:
        return "pong"

    def shutdown(self) -> None:
        self._server.shutdown()
