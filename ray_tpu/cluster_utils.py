"""In-process multi-node cluster fixture (ray: python/ray/cluster_utils.py:99).

The reference tests distributed behavior by booting extra raylet+plasma
processes with fake node IDs on one machine. Here nodes are virtual entries in
the scheduler's node table; each node gets its own worker processes, so
scheduling policy, spillback, node failure and actor restart are all
exercised for real while the object plane stays host-local (multi-host object
transfer is a later-round subsystem).
"""

from __future__ import annotations

from typing import Dict, Optional


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: Optional[Dict] = None):
        import ray_tpu
        from ray_tpu._private.runtime import get_runtime

        self._nodes = []
        if initialize_head:
            ray_tpu.init(**(head_node_args or {}))
        self._rt = get_runtime()
        self.head_node_id = self._rt.head_node_id

    def add_node(
        self,
        num_cpus: float = 1.0,
        resources: Optional[Dict] = None,
        labels: Optional[Dict[str, str]] = None,
        daemon: bool = False,
        store_root: Optional[str] = None,
    ) -> str:
        """labels: node metadata; "mesh_coord" (e.g. "0,1") marks the host's
        ICI torus coordinate, consumed by the MESH placement strategy.

        daemon=True starts a REAL node-daemon process owning the node's
        worker pool AND node object store (the reference's extra-raylet
        Cluster mode, ray: cluster_utils.py:99) — killing it is a node
        failure.  store_root places that node's isolated object-store
        directory (tests use distinct roots to prove no path sharing)."""
        if daemon:
            nid = self._rt.add_daemon_node(
                num_cpus=num_cpus, resources=resources, labels=labels,
                store_root=store_root,
            )
        else:
            nid = self._rt.add_node(
                num_cpus=num_cpus, resources=resources, labels=labels
            )
        self._nodes.append(nid)
        return nid

    def kill_node_daemon(self, node_id: str) -> None:
        """Hard-kill a daemon node's process (fault injection — the
        reference's NodeKillerActor pattern, test_utils.py:1347)."""
        proc = self._rt._daemon_procs.get(node_id)
        if proc is not None:
            proc.kill()

    def remove_node(self, node_id: str) -> None:
        self._rt.remove_node(node_id)
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def shutdown(self) -> None:
        import ray_tpu

        ray_tpu.shutdown()
