"""Language-model training step: loss, optimizer state, pjit factory.

This is the compiled SPMD "inner loop" that the Train library (ray_tpu.train)
drives from host actors — the TPU replacement for the reference's
DDP-wrapped user loop (python/ray/train/torch/train_loop_utils.py:92-98 +
NCCL allreduce).  Gradient reduction is not a runtime call: the mesh sharding
of params/batch makes XLA emit reduce-scatter/all-reduce over ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    param_axes,
)
from ray_tpu.parallel.mesh import build_mesh
from ray_tpu.parallel.sharding import (
    Rules,
    fit_shardings,
    logical_to_spec,
    resolve_rules,
    tree_shardings,
)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def cross_entropy_loss(
    logits: jax.Array, targets: jax.Array, mask: Optional[jax.Array] = None
) -> jax.Array:
    """Mean next-token cross entropy. logits [B,S,V] f32, targets [B,S]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return -jnp.mean(ll)
    mask = mask.astype(jnp.float32)
    return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def default_optimizer(
    learning_rate: float = 3e-4, weight_decay: float = 0.1, **kw
) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay, **kw),
    )


class LMTrainContext:
    """Sharded init/train-step bundle for one (config, mesh, rules) triple.

    Holds the jitted functions with in/out shardings attached so the host
    code never calls device_put by hand.
    """

    def __init__(
        self,
        config: TransformerConfig,
        mesh: Optional[Mesh] = None,
        strategy: str | Rules = "fsdp",
        optimizer: Optional[optax.GradientTransformation] = None,
    ):
        self.config = config
        self.mesh = mesh if mesh is not None else build_mesh()
        self.rules = resolve_rules(strategy)
        self.optimizer = optimizer or default_optimizer()

        raw_shardings = tree_shardings(param_axes(config), self.rules, self.mesh)
        abstract_params = jax.eval_shape(lambda: init_params(config, jax.random.PRNGKey(0)))
        self.param_shardings = fit_shardings(abstract_params, raw_shardings)
        # Optimizer state must be PINNED to the param shardings, not left to
        # propagation: XLA happily replicates adam moments (measured with
        # pp_fsdp), silently forfeiting the ZeRO optimizer-state sharding
        # that is fsdp's whole memory win.  Optax states mirror the param
        # tree, so match moment leaves to param leaves by shape; ambiguous
        # shapes (same shape, different sharding) fall back to propagation.
        self.repl = NamedSharding(self.mesh, P())
        shape_to_sharding: dict = {}
        for pleaf, psh in zip(
            jax.tree_util.tree_leaves(abstract_params),
            jax.tree_util.tree_leaves(self.param_shardings),
        ):
            prev = shape_to_sharding.get(pleaf.shape, psh)
            shape_to_sharding[pleaf.shape] = psh if prev == psh else None
        abstract_opt = jax.eval_shape(self.optimizer.init, abstract_params)
        self.opt_shardings = jax.tree_util.tree_map(
            lambda l: self.repl if l.ndim == 0 else shape_to_sharding.get(l.shape),
            abstract_opt,
        )
        self.batch_sharding = NamedSharding(
            self.mesh, logical_to_spec(("act_batch", "act_seq"), self.rules)
        )

        cfg, rules, opt = self.config, self.rules, self.optimizer

        def _init(key):
            params = init_params(cfg, key)
            opt_state = opt.init(params)
            return {"params": params, "opt_state": opt_state, "step": jnp.zeros((), jnp.int32)}

        self._init = jax.jit(
            _init,
            out_shardings={
                "params": self.param_shardings,
                "opt_state": self.opt_shardings,
                "step": self.repl,
            },
        )

        def _train_step(state, batch):
            def loss_fn(params):
                logits = forward(params, batch["tokens"], cfg, rules=rules, mesh=self.mesh)
                return cross_entropy_loss(logits, batch["targets"], batch.get("mask"))

            loss, grads = jax.value_and_grad(loss_fn)(state["params"])
            updates, opt_state = opt.update(grads, state["opt_state"], state["params"])
            params = optax.apply_updates(state["params"], updates)
            metrics = {
                "loss": loss,
                "grad_norm": optax.global_norm(grads),
                "step": state["step"] + 1,
            }
            return (
                {"params": params, "opt_state": opt_state, "step": state["step"] + 1},
                metrics,
            )

        # State out_shardings pinned, not propagated: GSPMD was measured to
        # replicate adam moments when left to choose, silently forfeiting
        # ZeRO optimizer-state sharding after the first step.
        self._train_step = jax.jit(
            _train_step,
            out_shardings=(
                {
                    "params": self.param_shardings,
                    "opt_state": self.opt_shardings,
                    "step": self.repl,
                },
                self.repl,
            ),
            donate_argnums=(0,),
        )

        def _forward(params, tokens):
            return forward(params, tokens, cfg, rules=rules, mesh=self.mesh)

        self._forward = jax.jit(_forward)

    # -- public API -------------------------------------------------------
    def init_state(self, seed: int = 0) -> Dict[str, Any]:
        with self.mesh:
            return self._init(jax.random.PRNGKey(seed))

    def make_batch(self, batch) -> Dict[str, jax.Array]:
        """Shard a host batch (pytree of [B, S] numpy arrays, every process
        holding the same global batch) onto the mesh.  make_array_from_callback
        hands each device its shard, which also works when the mesh spans
        processes (multi-host SPMD)."""
        import numpy as np

        def put(x):
            x = np.asarray(x)
            return jax.make_array_from_callback(
                x.shape, self.batch_sharding, lambda idx: x[idx]
            )

        return jax.tree_util.tree_map(put, batch)

    def train_step(self, state, batch) -> Tuple[Dict, Dict]:
        if not all(isinstance(x, jax.Array) for x in jax.tree_util.tree_leaves(batch)):
            batch = self.make_batch(batch)
        with self.mesh:
            state, metrics = self._train_step(state, batch)
        return state, metrics

    def apply(self, params, tokens) -> jax.Array:
        with self.mesh:
            return self._forward(params, tokens)
