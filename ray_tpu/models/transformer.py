"""Flagship model: Llama-family decoder-only transformer, TPU-first.

The reference has no model code of its own (it trains user-supplied torch
models through wrappers — python/ray/train/torch/train_loop_utils.py:92-98);
a TPU framework needs first-party models whose sharding the Train layer can
drive.  Design:

- Pure-functional: params are a plain pytree; `forward` is a jit-able
  function.  No module framework in the hot path.
- Every parameter leaf has a *logical axes* annotation (`param_axes`), mapped
  to mesh axes by ray_tpu.parallel.sharding rules — one model, every
  parallelism strategy (DP/FSDP/TP/SP via rules, not rewrites).
- Layers are stacked on a leading `layers` axis and run under `lax.scan`
  (one compiled layer body, O(1) compile time in depth) with optional
  `jax.checkpoint` rematerialization for HBM.
- Attention dispatches to the pallas flash kernel on TPU, blockwise scan
  otherwise (ray_tpu.ops.attention), or ring attention when the mesh has a
  nontrivial `seq` axis.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import dot_product_attention
from ray_tpu.ops.rotary import apply_rope
from ray_tpu.parallel.sharding import Rules, with_logical_constraint


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    tie_embeddings: bool = False
    remat: bool = True
    # Remat granularity: None = full per-layer recompute (min memory);
    # "attn" = save the attention kernel output (skips re-running the flash
    # kernel in backward); "qkv_attn" = additionally save post-rope q/k/v
    # (skips qkv matmul + rope recompute).  More saved = more HBM.
    remat_policy: Optional[str] = None
    attention_impl: Optional[str] = None  # None=auto, see ops.attention
    # Microbatches per pipeline-stage schedule when the rules shard the
    # layer stack over the `pipeline` axis (strategy="pp"/"pp_fsdp").
    # None derives min(4 * n_stages, local batch) — ~20% GPipe bubble
    # without slicing microbatches below MXU-efficient sizes.
    pp_microbatches: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    # -- presets ---------------------------------------------------------
    @staticmethod
    def tiny(**kw) -> "TransformerConfig":
        """Test-scale model for CPU-mesh tests."""
        base = dict(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=128, dtype=jnp.float32, remat=False,
        )
        base.update(kw)
        return TransformerConfig(**base)

    @staticmethod
    def llama_1b(**kw) -> "TransformerConfig":
        base = dict(
            vocab_size=32000, d_model=2048, n_layers=16, n_heads=16,
            n_kv_heads=16, d_ff=5504, max_seq_len=2048,
        )
        base.update(kw)
        return TransformerConfig(**base)

    @staticmethod
    def llama_7b(**kw) -> "TransformerConfig":
        """The north-star 7B config (BASELINE.json)."""
        base = dict(
            vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=32, d_ff=11008, max_seq_len=4096,
        )
        base.update(kw)
        return TransformerConfig(**base)

    def num_params(self) -> int:
        e = self.vocab_size * self.d_model
        attn = self.d_model * self.head_dim * (2 * self.n_heads + 2 * self.n_kv_heads)
        mlp = 3 * self.d_model * self.d_ff
        norms = 2 * self.d_model
        out = 0 if self.tie_embeddings else self.vocab_size * self.d_model
        return e + self.n_layers * (attn + mlp + norms) + self.d_model + out


def param_axes(config: TransformerConfig) -> Dict:
    """Pytree of logical-axes tuples, congruent with init_params output."""
    L = ("layers",)
    axes = {
        "embed": {"tokens": ("vocab", "embed")},
        "layers": {
            "attn": {
                "wq": L + ("embed", "heads", "head_dim"),
                "wk": L + ("embed", "kv_heads", "head_dim"),
                "wv": L + ("embed", "kv_heads", "head_dim"),
                "wo": L + ("heads", "head_dim", "embed"),
            },
            "mlp": {
                "w_gate": L + ("embed", "mlp"),
                "w_up": L + ("embed", "mlp"),
                "w_down": L + ("mlp", "embed"),
            },
            "ln1": L + (None,),
            "ln2": L + (None,),
        },
        "final_norm": (None,),
    }
    if not config.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init_params(config: TransformerConfig, key: jax.Array) -> Dict:
    """Initialize the parameter pytree (truncated-normal / scaled init)."""
    c = config
    k = iter(jax.random.split(key, 16))
    pd = c.param_dtype

    def norm_init(kk, shape, scale):
        return (jax.random.normal(kk, shape, jnp.float32) * scale).astype(pd)

    hd = c.head_dim
    L = c.n_layers
    emb_scale = c.d_model ** -0.5
    proj_scale = c.d_model ** -0.5
    out_scale = (2 * c.n_layers * c.d_model) ** -0.5  # GPT-2-style depth scaling

    params = {
        "embed": {"tokens": norm_init(next(k), (c.vocab_size, c.d_model), emb_scale)},
        "layers": {
            "attn": {
                "wq": norm_init(next(k), (L, c.d_model, c.n_heads, hd), proj_scale),
                "wk": norm_init(next(k), (L, c.d_model, c.n_kv_heads, hd), proj_scale),
                "wv": norm_init(next(k), (L, c.d_model, c.n_kv_heads, hd), proj_scale),
                "wo": norm_init(next(k), (L, c.n_heads, hd, c.d_model), out_scale),
            },
            "mlp": {
                "w_gate": norm_init(next(k), (L, c.d_model, c.d_ff), proj_scale),
                "w_up": norm_init(next(k), (L, c.d_model, c.d_ff), proj_scale),
                "w_down": norm_init(next(k), (L, c.d_ff, c.d_model), out_scale),
            },
            "ln1": jnp.ones((L, c.d_model), pd),
            "ln2": jnp.ones((L, c.d_model), pd),
        },
        "final_norm": jnp.ones((c.d_model,), pd),
    }
    if not c.tie_embeddings:
        params["lm_head"] = norm_init(next(k), (c.d_model, c.vocab_size), emb_scale)
    return params


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight.astype(x.dtype)


def _ambient_mesh():
    """The mesh from an enclosing `with mesh:` scope, if any."""
    try:
        # jax.interpreters.pxla.thread_resources is deprecated (jax 0.8.2+);
        # the underlying accessor lives in jax._src.mesh.
        from jax._src.mesh import thread_resources
    except ImportError:  # future relocation: fall back to the deprecated path
        from jax.interpreters.pxla import thread_resources

    m = thread_resources.env.physical_mesh
    return None if m.empty else m


def _fitting_axis(axis, mesh, dim: int) -> Optional[str]:
    """Resolve a rules entry to a single mesh axis name that divides dim."""
    if axis is None or mesh is None:
        return None
    if isinstance(axis, tuple):
        axis = axis[0] if axis else None
    if axis not in mesh.axis_names:
        return None
    return axis if dim % mesh.shape[axis] == 0 and mesh.shape[axis] > 1 else None


def _ring_axis(rules: Optional[Rules], mesh, q: jax.Array) -> Optional[str]:
    """The mesh axis to run ring attention over, or None for local attention.

    Non-None iff the strategy shards act_seq onto a real (>1) mesh axis that
    divides the sequence length — exactly the case where plain attention
    would silently all-gather the sequence."""
    if rules is None:
        return None
    return _fitting_axis(rules.get("act_seq"), mesh, q.shape[1])


def _layer(
    x: jax.Array,
    layer_params: Dict,
    positions: jax.Array,
    config: TransformerConfig,
    rules: Optional[Rules],
    mesh=None,
):
    c = config

    def constrain(h, axes):
        if rules is None:
            return h
        return with_logical_constraint(h, axes, rules, mesh)

    dt = c.dtype
    h = rms_norm(x, layer_params["ln1"], c.norm_eps)
    q = jnp.einsum("bse,ehd->bshd", h, layer_params["attn"]["wq"].astype(dt))
    kk = jnp.einsum("bse,ehd->bshd", h, layer_params["attn"]["wk"].astype(dt))
    vv = jnp.einsum("bse,ehd->bshd", h, layer_params["attn"]["wv"].astype(dt))
    q = constrain(q, ("act_batch", "act_seq", "act_heads", "act_head_dim"))
    kk = constrain(kk, ("act_batch", "act_seq", "act_kv_heads", "act_head_dim"))
    q = apply_rope(q, positions, theta=c.rope_theta)
    kk = apply_rope(kk, positions, theta=c.rope_theta)
    from jax.ad_checkpoint import checkpoint_name

    q = checkpoint_name(q, "q")
    kk = checkpoint_name(kk, "k")
    vv = checkpoint_name(vv, "v")
    ring_mesh = mesh if mesh is not None else _ambient_mesh()
    ring_axis = _ring_axis(rules, ring_mesh, q)
    if ring_axis is not None:
        # Sequence parallelism: activations are seq-sharded, so full
        # attention would force XLA to all-gather the sequence.  Ring
        # attention keeps KV rotating over ICI instead
        # (ops/ring_attention.py; SURVEY.md §5.7 — novel, no reference
        # counterpart).
        from ray_tpu.ops.ring_attention import ring_attention_sharded

        head_ax = _fitting_axis(rules.get("act_heads"), ring_mesh, q.shape[2])
        if head_ax is not None and kk.shape[2] % ring_mesh.shape[head_ax] != 0:
            head_ax = None  # GQA kv heads don't divide: replicate heads
        attn = ring_attention_sharded(
            q, kk, vv, ring_mesh,
            seq_axis=ring_axis,
            batch_axes=rules.get("act_batch"),
            head_axis=head_ax,
            causal=True,
        )
    else:
        attn = dot_product_attention(q, kk, vv, causal=True, impl=c.attention_impl)
    attn = checkpoint_name(attn, "attn")
    attn_out = jnp.einsum("bshd,hde->bse", attn, layer_params["attn"]["wo"].astype(dt))
    x = x + constrain(attn_out, ("act_batch", "act_seq", "act_embed"))

    h = rms_norm(x, layer_params["ln2"], c.norm_eps)
    gate = jnp.einsum("bse,ef->bsf", h, layer_params["mlp"]["w_gate"].astype(dt))
    up = jnp.einsum("bse,ef->bsf", h, layer_params["mlp"]["w_up"].astype(dt))
    ff = constrain(jax.nn.silu(gate) * up, ("act_batch", "act_seq", "act_mlp"))
    down = jnp.einsum("bsf,fe->bse", ff, layer_params["mlp"]["w_down"].astype(dt))
    x = x + constrain(down, ("act_batch", "act_seq", "act_embed"))
    return x


def _remat_policy(config: TransformerConfig):
    """Validated checkpoint policy for the configured remat granularity
    (shared by the scan and pipeline paths)."""
    if config.remat_policy == "attn":
        return jax.checkpoint_policies.save_only_these_names("attn")
    if config.remat_policy == "qkv_attn":
        return jax.checkpoint_policies.save_only_these_names("q", "k", "v", "attn")
    if config.remat_policy is None:
        # Save nothing per layer (full recompute in bwd) — the minimum-
        # memory mode long-context configs rely on (at 16k the qkv_attn
        # stash alone is ~5 GB on the bench model, past v5e HBM).
        return None
    raise ValueError(
        f"unknown remat_policy {config.remat_policy!r}; "
        "expected None, 'attn', or 'qkv_attn'"
    )


def _run_layers_pipelined(
    layer_params: Dict,
    x: jax.Array,
    positions: jax.Array,
    config: TransformerConfig,
    mesh,
    axis: str,
    rules: Optional[Dict] = None,
    fsdp_axis: Optional[str] = None,
) -> jax.Array:
    """Run the [L, ...] layer stack as a GPipe pipeline: the stack reshapes
    to [P, L/P, ...] (stage-major), each pipeline-axis device scans its own
    L/P layers, and microbatches stream between stages with ppermute
    (parallel/pipeline.py).

    With `fsdp_axis` (strategy "pp_fsdp"), each stage's params additionally
    live SHARDED over that axis and are all-gathered once per step inside
    the stage body — optimizer state and params-at-rest take 1/(P*F) of the
    model per device instead of 1/P."""
    from ray_tpu.parallel.pipeline import pipeline_apply

    c = config
    n_stages = mesh.shape[axis]
    per_stage = c.n_layers // n_stages

    stacked = jax.tree_util.tree_map(
        lambda a: a.reshape(n_stages, per_stage, *a.shape[1:]), layer_params
    )

    fsdp_dims = None
    if fsdp_axis is not None and rules is not None:
        layer_axes = param_axes(c)["layers"]

        def dim_for(axes_tuple):
            # stacked leaf dims: [P, L/P, *per-layer dims]; logical name i
            # (after the leading "layers") lands at stacked dim i + 2.
            for i, name in enumerate(axes_tuple[1:]):
                if name is not None and rules.get(name) == fsdp_axis:
                    return i + 2
            return None

        fsdp_dims = jax.tree_util.tree_map(
            dim_for, layer_axes,
            is_leaf=lambda t: isinstance(t, tuple),
        )

    def stage_fn(stage_params, h):
        def body(carry, lp):
            return _layer(carry, lp, positions, c, None, None), None

        out, _ = jax.lax.scan(body, h, stage_params)
        return out

    if c.remat:
        stage_fn = jax.checkpoint(stage_fn, policy=_remat_policy(c))
    return pipeline_apply(
        stage_fn, stacked, x, mesh,
        n_microbatches=c.pp_microbatches, axis=axis,
        fsdp_dims=fsdp_dims, fsdp_axis=fsdp_axis or "fsdp",
    )


def forward(
    params: Dict,
    tokens: jax.Array,
    config: TransformerConfig,
    *,
    rules: Optional[Rules] = None,
    mesh=None,
) -> jax.Array:
    """Token ids [B, S] -> logits [B, S, vocab] (f32)."""
    c = config
    x = params["embed"]["tokens"].astype(c.dtype)[tokens]
    if rules is not None:
        x = with_logical_constraint(x, ("act_batch", "act_seq", "act_embed"), rules, mesh)
    positions = jnp.arange(tokens.shape[1])

    # Pipeline parallelism: rules shard the LAYER STACK over the pipeline
    # axis — run the GPipe microbatch schedule instead of a plain scan
    # (each stage device holds n_layers/P layers).
    pp_mesh = mesh if mesh is not None else _ambient_mesh()
    pp_axis = None
    if rules is not None and rules.get("layers") is not None:
        ax = rules["layers"]
        ax = ax[0] if isinstance(ax, tuple) else ax
        size = pp_mesh.shape[ax] if (pp_mesh is not None and ax in pp_mesh.axis_names) else 1
        if size > 1:
            # Explicit pp intent: misconfigurations are ERRORS, not silent
            # fallbacks — replicated layers instead of pipelining would only
            # surface as OOM/low MFU at scale.
            if c.n_layers % size != 0:
                raise ValueError(
                    f"strategy 'pp': n_layers={c.n_layers} not divisible by "
                    f"pipeline axis size {size}"
                )
            sharded_params = [
                k for k in ("embed", "heads", "kv_heads", "head_dim", "mlp",
                            "vocab", "expert")
                if rules.get(k) is not None
            ]
            # fsdp-at-rest composes with pp (strategy "pp_fsdp"): the
            # sharded param axes are all-gathered per stage per step inside
            # the schedule.  TP-style axes (which also shard activations)
            # do NOT — gathering them would silently undo the tensor split.
            act_axes = set()
            for k, v in rules.items():
                if k.startswith("act_") and k != "act_batch" and v is not None:
                    act_axes.update(v if isinstance(v, tuple) else (v,))
            pp_fsdp_axes = set()
            bad = []
            for k in sharded_params:
                v = rules[k]
                if isinstance(v, tuple) or v == ax or v in act_axes:
                    bad.append(k)
                else:
                    pp_fsdp_axes.add(v)
            if bad:
                raise ValueError(
                    "strategy 'pp' composes with data sharding and ONE "
                    "fsdp-at-rest param axis (strategy 'pp_fsdp'); param "
                    f"dims {bad} shard over activation/tensor axes the "
                    "pipeline schedule cannot gather away"
                )
            if len(pp_fsdp_axes) > 1:
                raise ValueError(
                    "strategy 'pp' composes with at most ONE fsdp-at-rest "
                    f"param axis, got {sorted(pp_fsdp_axes)} across "
                    f"{sharded_params}"
                )
            pp_axis = ax
            pp_fsdp_axis = pp_fsdp_axes.pop() if pp_fsdp_axes else None
    if pp_axis is not None:
        x = _run_layers_pipelined(
            params["layers"], x, positions, c, pp_mesh, pp_axis,
            rules=rules, fsdp_axis=pp_fsdp_axis,
        )
    else:
        layer_fn = functools.partial(
            _layer, positions=positions, config=c, rules=rules, mesh=mesh
        )
        if c.remat:
            layer_fn = jax.checkpoint(layer_fn, policy=_remat_policy(c))

        def scan_body(carry, layer_params):
            return layer_fn(carry, layer_params), None

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    head = (
        params["embed"]["tokens"].T if c.tie_embeddings else params["lm_head"]
    ).astype(c.dtype)
    logits = jnp.einsum("bse,ev->bsv", x, head).astype(jnp.float32)
    if rules is not None:
        logits = with_logical_constraint(
            logits, ("act_batch", "act_seq", "act_vocab"), rules, mesh
        )
    return logits
