"""ray_tpu.models: first-party TPU-native model families.

The reference ships no models of its own (torch wrappers only); here the
model zoo is part of the framework so Train/Serve/RLlib drive real sharded
JAX programs.
"""

from ray_tpu.models.lm import (
    LMTrainContext,
    cross_entropy_loss,
    default_optimizer,
)
from ray_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    param_axes,
)

__all__ = [
    "LMTrainContext",
    "TransformerConfig",
    "cross_entropy_loss",
    "default_optimizer",
    "forward",
    "init_params",
    "param_axes",
]
