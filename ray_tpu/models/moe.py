"""Mixture-of-Experts block: expert-parallel FFN for the transformer.

No counterpart in the reference (SURVEY §2.4: EP absent) — built TPU-first:
experts live on a leading `expert` dim sharded over the `expert` mesh axis
(ep_rules, parallel/sharding.py); routing is top-k softmax gating and the
token shuffle compiles to all-to-alls over ICI when XLA partitions the
gather/scatter by expert.

Dense-compute formulation (einsum over a one-hot dispatch mask rather than
ragged gather): identical math to token-dropping MoE with capacity, and
every op is a static-shape matmul the MXU likes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import Rules, with_logical_constraint


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    d_model: int = 64
    d_ff: int = 128
    # tokens each expert processes per batch = capacity_factor * T * k / E
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01  # load-balance loss weight (Switch-style)


def moe_param_axes(cfg: MoEConfig) -> Dict:
    return {
        "router": ("embed", "expert"),
        "w_gate": ("expert", "embed", "mlp"),
        "w_up": ("expert", "embed", "mlp"),
        "w_down": ("expert", "mlp", "embed"),
    }


def init_moe_params(cfg: MoEConfig, key: jax.Array, dtype=jnp.float32) -> Dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    scale = cfg.d_model ** -0.5
    return {
        "router": (jax.random.normal(k1, (cfg.d_model, cfg.n_experts)) * scale).astype(dtype),
        "w_gate": (jax.random.normal(k2, (cfg.n_experts, cfg.d_model, cfg.d_ff)) * scale).astype(dtype),
        "w_up": (jax.random.normal(k3, (cfg.n_experts, cfg.d_model, cfg.d_ff)) * scale).astype(dtype),
        "w_down": (jax.random.normal(k4, (cfg.n_experts, cfg.d_ff, cfg.d_model)) * scale).astype(dtype),
    }


def moe_ffn(
    params: Dict,
    x: jax.Array,
    cfg: MoEConfig,
    *,
    rules: Optional[Rules] = None,
    mesh=None,
) -> Tuple[jax.Array, jax.Array]:
    """x [B, S, D] → (y [B, S, D], aux_loss scalar).

    Dispatch: top-k router → per-expert capacity-limited one-hot combine
    tensor → einsum dispatch/experts/combine.  With ep_rules the expert dim
    of params+intermediates shards over the `expert` axis and XLA inserts
    the token all-to-alls.
    """
    B, S, D = x.shape
    E, K = cfg.n_experts, cfg.top_k
    T = B * S
    capacity = max(int(cfg.capacity_factor * T * K / E), K)

    def constrain(h, axes):
        if rules is None:
            return h
        return with_logical_constraint(h, axes, rules, mesh)

    tokens = x.reshape(T, D)
    logits = tokens @ params["router"].astype(x.dtype)  # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # Top-k expert choice per token.
    gate_vals, expert_idx = jax.lax.top_k(probs, K)  # [T, K]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9
    )

    # Capacity: position of each token within its chosen expert's queue;
    # tokens past capacity drop (standard Switch behavior).
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, K, E]
    position_in_expert = (
        jnp.cumsum(onehot.reshape(T * K, E), axis=0).reshape(T, K, E) - 1.0
    )
    within_cap = position_in_expert < capacity
    onehot = onehot * within_cap

    # combine [T, E, C]: weight of each token at its slot in each expert.
    pos = jnp.einsum("tke,tke->tk", position_in_expert, onehot).astype(jnp.int32)
    slot_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [T,K,C]
    combine = jnp.einsum(
        "tk,tke,tkc->tec", gate_vals.astype(jnp.float32), onehot, slot_onehot
    )
    dispatch = (combine > 0).astype(x.dtype)  # [T, E, C]

    # Expert compute: [E, C, D] batched matmuls, expert dim sharded.
    expert_in = jnp.einsum("td,tec->ecd", tokens, dispatch)
    expert_in = constrain(expert_in, ("act_expert", None, "act_embed"))
    g = jnp.einsum("ecd,edf->ecf", expert_in, params["w_gate"].astype(x.dtype))
    u = jnp.einsum("ecd,edf->ecf", expert_in, params["w_up"].astype(x.dtype))
    h = jax.nn.silu(g) * u
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_down"].astype(x.dtype))
    expert_out = constrain(expert_out, ("act_expert", None, "act_embed"))

    y = jnp.einsum("ecd,tec->td", expert_out, combine.astype(x.dtype))

    # Switch load-balance aux loss: E * sum_e(frac_tokens_e * frac_probs_e).
    frac_tokens = onehot[:, 0, :].mean(axis=0)  # top-1 assignment share
    frac_probs = probs.mean(axis=0)
    aux = cfg.router_aux_coef * E * jnp.sum(frac_tokens * frac_probs)

    return y.reshape(B, S, D).astype(x.dtype), aux
