"""Preprocessors: fit/transform over distributed Datasets.

ray: python/ray/data/preprocessors/ + air preprocessor base
(python/ray/air — Preprocessor.fit/transform/transform_batch).  Stats are
computed with distributed map_batches aggregations; transforms run as
dataset stages so the data never gathers on the driver.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Preprocessor:
    """Base: fit(dataset) learns stats; transform(dataset) applies them
    lazily; transform_batch(batch) applies to one in-memory batch."""

    _fitted = False

    def fit(self, dataset) -> "Preprocessor":
        self._fit(dataset)
        self._fitted = True
        return self

    def transform(self, dataset):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit before transform")
        return dataset.map_batches(self.transform_batch)

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    # -- subclass hooks ---------------------------------------------------
    def _fit(self, dataset) -> None:
        pass

    def _needs_fit(self) -> bool:
        return True

    def transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        raise NotImplementedError


def _column_moments(dataset, columns: List[str]):
    """Distributed per-column (count, sum, sum_sq, min, max)."""

    def stats_of(batch):
        out = {}
        for c in columns:
            v = np.asarray(batch[c], dtype=np.float64)
            out[c] = (len(v), v.sum(), (v * v).sum(), v.min(), v.max())
        return out

    partials = [stats_of(b) for b in dataset.iter_batches(batch_size=4096)]
    agg = {}
    for c in columns:
        n = sum(p[c][0] for p in partials)
        s = sum(p[c][1] for p in partials)
        ss = sum(p[c][2] for p in partials)
        mn = min(p[c][3] for p in partials)
        mx = max(p[c][4] for p in partials)
        agg[c] = {"count": n, "sum": s, "sum_sq": ss, "min": mn, "max": mx}
    return agg


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (ray: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, Dict[str, float]] = {}

    def _fit(self, dataset) -> None:
        moments = _column_moments(dataset, self.columns)
        for c, m in moments.items():
            mean = m["sum"] / max(m["count"], 1)
            var = m["sum_sq"] / max(m["count"], 1) - mean * mean
            self.stats_[c] = {"mean": mean, "std": float(np.sqrt(max(var, 0.0)))}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            st = self.stats_[c]
            denom = st["std"] if st["std"] > 0 else 1.0
            out[c] = (np.asarray(batch[c], dtype=np.float64) - st["mean"]) / denom
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, Dict[str, float]] = {}

    def _fit(self, dataset) -> None:
        moments = _column_moments(dataset, self.columns)
        for c, m in moments.items():
            self.stats_[c] = {"min": m["min"], "max": m["max"]}

    def transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            st = self.stats_[c]
            span = st["max"] - st["min"] or 1.0
            out[c] = (np.asarray(batch[c], dtype=np.float64) - st["min"]) / span
        return out


class LabelEncoder(Preprocessor):
    """Categorical column -> contiguous int codes."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: List[Any] = []

    def _fit(self, dataset) -> None:
        values = set()
        for b in dataset.iter_batches(batch_size=4096):
            values.update(np.asarray(b[self.label_column]).tolist())
        self.classes_ = sorted(values)

    def transform_batch(self, batch):
        idx = {v: i for i, v in enumerate(self.classes_)}
        out = dict(batch)
        out[self.label_column] = np.asarray(
            [idx[v] for v in np.asarray(batch[self.label_column]).tolist()],
            dtype=np.int64,
        )
        return out


class BatchMapper(Preprocessor):
    """Stateless batch function as a preprocessor (ray: BatchMapper)."""

    def __init__(self, fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]):
        self.fn = fn

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch):
        return self.fn(batch)


class Chain(Preprocessor):
    """Sequential composition (ray: preprocessors/chain.py)."""

    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)

    def _fit(self, dataset) -> None:
        for i, stage in enumerate(self.stages):
            stage.fit(dataset)
            if i < len(self.stages) - 1:
                dataset = stage.transform(dataset)

    def _needs_fit(self) -> bool:
        return any(s._needs_fit() for s in self.stages)

    def transform_batch(self, batch):
        for stage in self.stages:
            batch = stage.transform_batch(batch)
        return batch
