"""Checkpoint: morphable dict/directory checkpoint container.

ray: python/ray/air/checkpoint.py:63 — the reference's Checkpoint
interconverts dict/dir/URI/object-ref forms.  TPU-native additions: jax
pytrees are first-class (saved via orbax when materialized to a directory),
and sharded arrays are gathered/resharded through the mesh on load, so a
checkpoint written under one parallelism strategy restores under another.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Optional


class Checkpoint:
    """A checkpoint is either an in-memory dict or an on-disk directory."""

    _DICT_FILE = "checkpoint.pkl"

    def __init__(
        self,
        data: Optional[Dict[str, Any]] = None,
        directory: Optional[str] = None,
    ):
        if (data is None) == (directory is None):
            raise ValueError("provide exactly one of data= or directory=")
        self._data = data
        self._dir = directory
        self.id = uuid.uuid4().hex[:8]

    # -- constructors -----------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=str(path))

    @classmethod
    def from_jax_state(cls, state, **extra) -> "Checkpoint":
        """Checkpoint a jax pytree (host-fetched, strategy-agnostic).

        Gathers every leaf to host memory — simple and fine for small
        models, but O(model × hosts) DCN traffic + host RAM at scale; use
        from_jax_state_sharded for the big ones."""
        import jax

        host_state = jax.tree_util.tree_map(
            lambda x: _to_host(x), state
        )
        return cls.from_dict({"jax_state": host_state, **extra})

    @classmethod
    def from_jax_state_sharded(cls, state, directory: str, **extra) -> "Checkpoint":
        """Scalable save: orbax writes each host's OWN shards straight to
        `directory` (no cross-host gather, no full copy in host RAM — the
        fix for gathering a 7B state to every v5p-64 host).  The directory
        must be shared storage on multi-host; the returned checkpoint is a
        lightweight directory reference that ships over the control plane
        as a path, not as tensors."""
        import jax

        path = os.path.abspath(directory)
        os.makedirs(path, exist_ok=True)
        if jax.process_count() > 1:
            # Multi-host genuinely requires orbax (the pickle fallback can't
            # save non-addressable arrays and hosts would race on one file):
            # let any orbax failure propagate.
            import orbax.checkpoint as ocp

            ocp.PyTreeCheckpointer().save(
                os.path.join(path, "state"), state, force=True
            )
        else:
            _orbax_save(os.path.join(path, "state"), state)  # pickle fallback
        # Metadata pkl: exactly one writer on multi-host (orbax coordinates
        # the tensor save; this file would otherwise be truncated by
        # concurrent hosts).  Always written — to_dict()'s pkl branch is
        # what merges the orbax state back under 'jax_state'.
        if jax.process_index() == 0:
            tmp = os.path.join(path, cls._DICT_FILE + f".tmp-{os.getpid()}")
            with open(tmp, "wb") as f:
                pickle.dump(dict(extra), f)
            os.replace(tmp, os.path.join(path, cls._DICT_FILE))
        if jax.process_count() > 1:
            # Every host must see the complete directory (incl. the pkl just
            # written by process 0) before anyone reads the checkpoint back.
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("raytpu_sharded_ckpt")
        return cls.from_directory(path)

    # -- accessors --------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        """Full dict form.  Round-trips every key (the reference's
        Checkpoint.to_dict does): a directory checkpoint's orbax 'state'
        subdir is restored back under 'jax_state'.  Returns a copy so
        callers can't corrupt the checkpoint's internal dict."""
        if self._data is not None:
            return dict(self._data)
        fp = os.path.join(self._dir, self._DICT_FILE)
        if os.path.exists(fp):
            with open(fp, "rb") as f:
                data = pickle.load(f)
            state_dir = os.path.join(self._dir, "state")
            if "jax_state" not in data and os.path.isdir(state_dir):
                data["jax_state"] = _orbax_restore(state_dir)
            return data
        # orbax-format directory
        state = _orbax_restore(self._dir)
        return {"jax_state": state}

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="raytpu-ckpt-")
        os.makedirs(path, exist_ok=True)
        if self._dir is not None:
            if os.path.abspath(self._dir) != os.path.abspath(path):
                shutil.copytree(self._dir, path, dirs_exist_ok=True)
            return path
        data = dict(self._data)
        state = data.pop("jax_state", None)
        if state is not None:
            _orbax_save(os.path.join(path, "state"), state)
        with open(os.path.join(path, self._DICT_FILE), "wb") as f:
            pickle.dump(data, f)
        return path

    @contextmanager
    def as_directory(self):
        owned = self._dir is None
        path = self.to_directory()
        try:
            yield path
        finally:
            if owned:
                shutil.rmtree(path, ignore_errors=True)

    def get_jax_state(self, target=None, shardings=None):
        """Restore the saved pytree; with shardings, each leaf lands on the
        requested layout (cross-strategy restore).

        Directory checkpoints with shardings restore THROUGH orbax's
        restore_args — each host reads only its shards, never materializing
        the full state in host RAM (the scalable complement of
        from_jax_state_sharded)."""
        state_dir = (
            os.path.join(self._dir, "state") if self._dir is not None else None
        )
        if shardings is not None and state_dir and os.path.isdir(state_dir):
            state = _orbax_restore_sharded(state_dir, shardings)
            if state is not None:
                return state
        d = self.to_dict()
        state = d.get("jax_state")
        if state is None and state_dir:
            state = _orbax_restore(state_dir)
        if state is None:
            raise ValueError("checkpoint holds no jax state")
        if shardings is not None:
            import jax

            state = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s), state, shardings
            )
        return state

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir={self._dir!r}"
        return f"Checkpoint({kind}, id={self.id})"


def _to_host(x):
    import jax
    import numpy as np

    if isinstance(x, jax.Array):
        if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
            # Multi-host sharded array: gather the full value to every host.
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(x)
    return x


def _orbax_save(path: str, state) -> None:
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.abspath(path), state, force=True)
    except Exception:
        # orbax unavailable/incompatible: fall back to pickle
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "state.pkl"), "wb") as f:
            pickle.dump(state, f)


def _orbax_restore_sharded(path: str, shardings):
    """Restore each leaf straight onto its target sharding (every host
    reads only its own shards).  None when orbax/layout can't do it —
    callers fall back to the host-gather path."""
    if os.path.exists(os.path.join(path, "state.pkl")):
        return None  # pickle-fallback save: no sharded restore possible
    try:
        import jax
        import orbax.checkpoint as ocp

        restore_args = jax.tree_util.tree_map(
            lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings
        )
        ckptr = ocp.PyTreeCheckpointer()
        return ckptr.restore(os.path.abspath(path), restore_args=restore_args)
    except Exception as e:
        import warnings

        warnings.warn(
            f"sharded checkpoint restore failed ({e!r}); falling back to the "
            f"host-gather path — expect full-state host memory use"
        )
        return None


def _orbax_restore(path: str):
    pkl = os.path.join(path, "state.pkl")
    if os.path.exists(pkl):
        with open(pkl, "rb") as f:
            return pickle.load(f)
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    return ckptr.restore(os.path.abspath(path))
