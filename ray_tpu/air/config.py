"""AIR-style configs (ray: python/ray/air/config.py).

ScalingConfig gains TPU-native fields: instead of "num GPUs per worker" the
unit is chips per host-worker plus an optional mesh hint that the Train
backend turns into the global jax mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many train-worker actors, with what per-worker resources.

    ray: python/ray/air/config.py ScalingConfig (num_workers,
    use_gpu/resources_per_worker); TPU-native: chips_per_worker reserves the
    "TPU" resource, mesh_shape optionally fixes the global MeshSpec.
    """

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    mesh_shape: Optional[Dict[str, int]] = None  # MeshSpec kwargs

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {"CPU": 1.0})
        if self.use_tpu and self.chips_per_worker:
            res["TPU"] = float(self.chips_per_worker)
        return res


@dataclasses.dataclass
class FailureConfig:
    """ray: python/ray/air/config.py FailureConfig."""

    max_failures: int = 0  # group restarts before giving up; -1 = unlimited


@dataclasses.dataclass
class CheckpointConfig:
    """ray: python/ray/air/config.py CheckpointConfig."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    """ray: python/ray/air/config.py RunConfig."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    verbose: int = 1
    callbacks: Optional[list] = None
    # Stop criteria dict (ray: air.RunConfig(stop=...)): {result_key: bound}.
    # A trial stops when result[key] >= bound (<= for the tune metric when
    # mode="min").
    stop: Optional[Dict[str, float]] = None
