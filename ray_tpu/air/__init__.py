"""ray_tpu.air: shared ML plumbing (ray: python/ray/air/)."""

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result
from ray_tpu.train import session

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "session",
]
