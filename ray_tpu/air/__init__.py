"""ray_tpu.air: shared ML plumbing (ray: python/ray/air/)."""

from ray_tpu.air.batch_predictor import BatchPredictor, Predictor
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air import preprocessors
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result
from ray_tpu.train import session

__all__ = [
    "BatchPredictor",
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "Predictor",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "preprocessors",
    "session",
]
