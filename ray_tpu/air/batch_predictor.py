"""BatchPredictor: offline batched inference over a Dataset.

ray: python/ray/train/batch_predictor.py — loads a model from a Checkpoint
into N predictor ACTORS and streams dataset batches through them.
TPU-first: each predictor actor builds its jitted apply once, then every
batch is a single device dispatch; actors pull blocks via the object store
(no driver round-trip for the data).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """User-implemented: from_checkpoint builds state, predict maps a batch
    (ray: python/ray/train/predictor.py)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError


class _PredictorActor:
    def __init__(self, predictor_cls_blob: bytes, ckpt_dir: Optional[str], kwargs):
        import cloudpickle

        cls = cloudpickle.loads(predictor_cls_blob)
        ckpt = Checkpoint.from_directory(ckpt_dir) if ckpt_dir else None
        self.predictor = cls.from_checkpoint(ckpt, **(kwargs or {}))

    def predict_shard(self, shard, batch_size: int):
        """Run every batch of a Dataset shard; returns list of out-batches."""
        out = []
        for batch in shard.iter_batches(batch_size=batch_size):
            out.append(self.predictor.predict(batch))
        return out


class BatchPredictor:
    def __init__(self, checkpoint: Optional[Checkpoint], predictor_cls, **predictor_kwargs):
        import cloudpickle

        self._ckpt = checkpoint
        self._cls_blob = cloudpickle.dumps(predictor_cls)
        self._kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, predictor_cls, **kw) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **kw)

    def predict(
        self,
        dataset,
        *,
        batch_size: int = 256,
        num_actors: int = 2,
        num_tpus_per_actor: float = 0,
    ):
        """Dataset → Dataset of prediction batches."""
        from ray_tpu import data as rd

        ckpt_dir = self._ckpt.to_directory() if self._ckpt is not None else None
        opts: Dict[str, Any] = {"num_cpus": 1}
        if num_tpus_per_actor:
            opts["num_tpus"] = num_tpus_per_actor
        Actor = ray_tpu.remote(_PredictorActor)
        actors = [
            Actor.options(**opts).remote(self._cls_blob, ckpt_dir, self._kwargs)
            for _ in range(num_actors)
        ]
        try:
            shards = dataset.split(num_actors)
            refs = [
                a.predict_shard.remote(s, batch_size) for a, s in zip(actors, shards)
            ]
            all_batches = []
            for r in ray_tpu.get(refs, timeout=600):
                all_batches.extend(r)
            from ray_tpu.data.block import NumpyBlock

            blocks = [ray_tpu.put(NumpyBlock(b)) for b in all_batches if b]
            return rd.Dataset(blocks or [ray_tpu.put([])])
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
