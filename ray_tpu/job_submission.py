"""Job submission: run driver scripts as supervised subprocesses.

ray: dashboard/modules/job/ (JobSubmissionClient at sdk.py:40, job manager/
supervisor).  v0 scope: jobs run on the submitting machine as independent
driver processes (each job creates its own ray_tpu runtime), with captured
logs, status tracking, env_vars runtime env, and stop.  The surface
(submit/status/logs/list/stop/wait) matches the reference so cluster-level
execution can slot in behind it later.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"

_TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = PENDING
    submission_time: float = field(default_factory=time.time)
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    return_code: Optional[int] = None
    log_path: str = ""
    metadata: Dict[str, Any] = field(default_factory=dict)


class JobSubmissionClient:
    """ray: JobSubmissionClient (dashboard/modules/job/sdk.py:40)."""

    def __init__(self, log_dir: Optional[str] = None):
        import tempfile

        self._log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), f"raytpu-jobs-{os.getpid()}"
        )
        os.makedirs(self._log_dir, exist_ok=True)
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def submit_job(
        self,
        *,
        entrypoint: str,
        runtime_env: Optional[Dict[str, Any]] = None,
        metadata: Optional[Dict[str, Any]] = None,
        submission_id: Optional[str] = None,
    ) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:12]}"
        renv = runtime_env or {}
        pip_path = None
        if renv:
            from ray_tpu._private.runtime_env import (
                pip_env_dir,
                validate_runtime_env,
            )

            # Same submit-time contract as tasks/actors: typos and
            # conda/container fail fast with guidance, never silently drop.
            # Raises happen BEFORE the job registers — a rejected
            # submission must not leave a ghost PENDING entry (and the
            # submission_id stays reusable for the corrected retry).
            validate_runtime_env(renv)
            if renv.get("pip"):
                # Jobs run on this host: build/reuse the content-hashed
                # pip env and put it on the entrypoint's PYTHONPATH.
                pip_path = pip_env_dir([str(s) for s in renv["pip"]])
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already exists")
            info = JobInfo(
                job_id=job_id,
                entrypoint=entrypoint,
                log_path=os.path.join(self._log_dir, f"{job_id}.log"),
                metadata=dict(metadata or {}),
            )
            self._jobs[job_id] = info
        env = os.environ.copy()
        env.update({k: str(v) for k, v in (renv.get("env_vars") or {}).items()})
        cwd = renv.get("working_dir") or os.getcwd()
        paths = ([pip_path] if pip_path else []) + [
            p for p in (renv.get("py_modules") or [])
        ] + [cwd]
        if env.get("PYTHONPATH"):
            paths.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(paths)
        log_f = open(info.log_path, "wb")
        try:
            proc = subprocess.Popen(
                entrypoint,
                shell=True,
                cwd=cwd,
                env=env,
                stdout=log_f,
                stderr=subprocess.STDOUT,
                start_new_session=True,  # stop_job kills the whole group
            )
        except Exception as e:
            # No ghost PENDING jobs: record the spawn failure durably.
            log_f.write(f"job spawn failed: {e!r}\n".encode())
            log_f.close()
            with self._lock:
                info.status = FAILED
                info.end_time = time.time()
            raise
        log_f.close()
        stop_now = False
        with self._lock:
            if info.status == PENDING:
                info.status = RUNNING
                info.start_time = time.time()
            else:
                stop_now = True  # stop_job() won the race pre-Popen
            self._procs[job_id] = proc
        if stop_now:
            try:
                proc.terminate()
            except OSError:
                pass
        threading.Thread(
            target=self._supervise, args=(job_id, proc), daemon=True
        ).start()
        return job_id

    def _supervise(self, job_id: str, proc: subprocess.Popen) -> None:
        rc = proc.wait()
        with self._lock:
            info = self._jobs[job_id]
            info.end_time = time.time()
            info.return_code = rc
            if info.status != STOPPED:
                info.status = SUCCEEDED if rc == 0 else FAILED

    def get_job_status(self, job_id: str) -> str:
        with self._lock:
            return self._jobs[job_id].status

    def get_job_info(self, job_id: str) -> JobInfo:
        import copy

        with self._lock:
            return copy.copy(self._jobs[job_id])  # snapshot, not live state

    def get_job_logs(self, job_id: str) -> str:
        info = self.get_job_info(job_id)
        try:
            with open(info.log_path, "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def list_jobs(self) -> List[JobInfo]:
        import copy

        with self._lock:
            return [copy.copy(j) for j in self._jobs.values()]

    def stop_job(self, job_id: str) -> bool:
        import signal

        with self._lock:
            info = self._jobs.get(job_id)
            proc = self._procs.get(job_id)
            if info is None or info.status in _TERMINAL:
                return False
            info.status = STOPPED
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
        return True

    def wait_until_finish(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in _TERMINAL:
                return status
            time.sleep(0.1)
        raise TimeoutError(f"job {job_id} still {self.get_job_status(job_id)}")
