"""NodeProvider: the cloud-plugin seam of the autoscaler.

ray: python/ray/autoscaler/node_provider.py:13 (NodeProvider ABC) +
_private/fake_multi_node/node_provider.py:237 (FakeMultiNodeProvider).
Providers own machine lifecycle; the autoscaler decides HOW MANY of each
node type, the provider makes it so.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Subclass per cloud.  node_type -> resource shape comes from the
    autoscaler config's available_node_types table."""

    def __init__(self, provider_config: Optional[Dict[str, Any]] = None):
        self.provider_config = dict(provider_config or {})

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_resources(self, provider_node_id: str) -> Dict[str, float]:
        raise NotImplementedError

    def node_type(self, provider_node_id: str) -> str:
        raise NotImplementedError

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def runtime_node_id(self, provider_node_id: str) -> Optional[str]:
        """The ray_tpu cluster node id this machine registered as (None
        while still booting/joining)."""
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Test/dev provider: "machines" are in-process virtual nodes (or real
    node-daemon processes with daemon=True) of the current runtime — the
    analogue of FakeMultiNodeProvider, which starts extra raylets."""

    def __init__(self, provider_config: Optional[Dict[str, Any]] = None):
        super().__init__(provider_config)
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self.use_daemons = bool(self.provider_config.get("use_daemons", False))

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes.keys())

    def node_resources(self, provider_node_id: str) -> Dict[str, float]:
        return dict(self._nodes[provider_node_id]["resources"])

    def node_type(self, provider_node_id: str) -> str:
        return self._nodes[provider_node_id]["type"]

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        from ray_tpu._private.runtime import get_runtime

        rt = get_runtime()
        res = dict(resources)
        cpus = res.pop("CPU", 1.0)
        if self.use_daemons:
            nid = rt.add_daemon_node(num_cpus=cpus, resources=res)
        else:
            nid = rt.add_node(num_cpus=cpus, resources=res)
        pid = f"local-{uuid.uuid4().hex[:8]}"
        with self._lock:
            self._nodes[pid] = {
                "type": node_type,
                "resources": dict(resources),
                "runtime_node_id": nid,
            }
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        from ray_tpu._private.runtime import get_runtime

        with self._lock:
            info = self._nodes.pop(provider_node_id, None)
        if info is not None:
            get_runtime().remove_node(info["runtime_node_id"])

    def runtime_node_id(self, provider_node_id: str) -> Optional[str]:
        info = self._nodes.get(provider_node_id)
        return info["runtime_node_id"] if info else None


class TPUPodNodeProvider(NodeProvider):
    """GCP TPU-VM provider: node types are TPU slice shapes (e.g. v5p-8
    hosts) created via gcloud; each VM's startup script boots a node
    daemon pointed at the head, registering a PRE-ASSIGNED node id.

    The full lifecycle (create -> daemon joins -> TPU-shaped task
    schedules -> terminate) is exercised against a fake `gcloud`
    executable in tests/test_autoscaler_jobs.py — the real binary needs
    cloud credentials + egress, which CI doesn't have (the same
    fake-provider pattern as ray: autoscaler/_private/fake_multi_node).
    """

    def __init__(self, provider_config: Optional[Dict[str, Any]] = None):
        super().__init__(provider_config)
        self.project = self.provider_config.get("project")
        self.zone = self.provider_config.get("zone")
        self._nodes: Dict[str, Dict[str, Any]] = {}

    def _gcloud(self, *args: str) -> str:
        import subprocess

        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--project={self.project}", f"--zone={self.zone}", "--format=json"]
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            raise RuntimeError(f"gcloud failed: {out.stderr[-500:]}")
        return out.stdout

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes.keys())

    def node_resources(self, provider_node_id: str) -> Dict[str, float]:
        return dict(self._nodes[provider_node_id]["resources"])

    def node_type(self, provider_node_id: str) -> str:
        return self._nodes[provider_node_id]["type"]

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        # node_type e.g. "v5p-8"; boots a TPU VM whose startup script runs
        # `python -m ray_tpu._private.node_daemon` pointed at the head's
        # address, with a node_id PRE-ASSIGNED here — once the daemon
        # registers under it, runtime_node_id() flips from None (booting)
        # to the joined id, which is what the autoscaler's boot-timeout and
        # idle logic key on.
        name = f"raytpu-{node_type}-{uuid.uuid4().hex[:6]}"
        import json

        from ray_tpu._private import ids as _ids
        from ray_tpu._private.runtime import get_runtime

        rt = get_runtime()
        nid = _ids.node_id()
        _bind_host, port = rt.address
        # The driver's loopback bind address is useless to a remote VM: the
        # head host must come from provider config (and the driver must run
        # with RAY_TPU_BIND_HOST=0.0.0.0 or a routable interface).
        host = self.provider_config.get("head_host")
        if not host:
            raise ValueError(
                "TPUPodNodeProvider requires provider_config['head_host'] — "
                "a driver address the TPU VMs can route to"
            )
        if _bind_host in ("127.0.0.1", "localhost"):
            # Fail BEFORE billing a VM whose daemon can never connect.
            raise ValueError(
                "driver listener is bound to loopback; start the driver "
                "with RAY_TPU_BIND_HOST=0.0.0.0 (or a routable interface) "
                "so remote node daemons can reach it"
            )
        node_cfg = json.dumps(
            {
                "node_id": nid,
                "session": rt.session_name,
                "num_cpus": resources.get("CPU", 1),
                # full shape + labels: a TPU node registering CPU-only would
                # leave the TPU demand that triggered this launch infeasible
                "resources": {k: v for k, v in resources.items() if k != "CPU"},
                "labels": dict(self.provider_config.get("labels") or {}),
            }
        )
        # NOTE: a hardened deployment should deliver the authkey via a
        # secret manager rather than instance metadata.
        import shlex

        startup = (
            f"export RAY_TPU_DRIVER_HOST={shlex.quote(str(host))}; "
            f"export RAY_TPU_DRIVER_PORT={shlex.quote(str(port))}; "
            f"export RAY_TPU_AUTHKEY={shlex.quote(rt._authkey.hex())}; "
            f"export RAY_TPU_NODE_CONFIG={shlex.quote(node_cfg)}; "
            "python -m ray_tpu._private.node_daemon"
        )
        self._gcloud(
            "create", name, f"--accelerator-type={node_type}",
            "--version=tpu-ubuntu2204-base",
            f"--metadata=startup-script={startup}",
        )
        self._nodes[name] = {
            "type": node_type,
            "resources": dict(resources),
            "runtime_node_id": nid,
        }
        return name

    def terminate_node(self, provider_node_id: str) -> None:
        if provider_node_id in self._nodes:
            self._gcloud("delete", provider_node_id, "--quiet")
            self._nodes.pop(provider_node_id, None)

    def runtime_node_id(self, provider_node_id: str) -> Optional[str]:
        """None until the VM's daemon actually registers the node."""
        nid = self._nodes.get(provider_node_id, {}).get("runtime_node_id")
        if nid is None:
            return None
        from ray_tpu._private.runtime import get_runtime

        return nid if nid in get_runtime().state.nodes else None
