"""StandardAutoscaler: reconcile resource demand against the node fleet.

ray: python/ray/autoscaler/_private/autoscaler.py:168 (StandardAutoscaler,
update :366) + resource_demand_scheduler.py:103 (bin-packing demand into
node types) + load_metrics.py.  Demand comes straight from the runtime:
queued task resource shapes + pending placement-group bundles; supply is
the alive node table.  update() launches the cheapest node-type mix that
fits the unmet demand (first-fit-decreasing) and terminates nodes idle
longer than idle_timeout_s, within [min_workers, max_workers].
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider


@dataclass
class NodeTypeConfig:
    """One launchable machine shape (ray: available_node_types entries)."""

    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig] = field(default_factory=dict)
    idle_timeout_s: float = 60.0
    max_launch_batch: int = 8
    # A launched node that never joins the runtime within this window is
    # terminated (boot failure) — and until then its capacity counts as
    # in-flight so repeated update() passes don't re-launch for the same
    # demand (slow cloud boots would otherwise launch max_workers VMs).
    boot_timeout_s: float = 600.0


def _fits(have: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(have.get(k, 0.0) >= v - 1e-9 for k, v in need.items())


def _sub(have: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        have[k] = have.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(
        self,
        provider: NodeProvider,
        config: AutoscalerConfig,
    ):
        self.provider = provider
        self.config = config
        self._idle_since: Dict[str, float] = {}  # provider node id -> ts
        self._launching: Dict[str, Tuple[str, float]] = {}  # pid -> (type, ts)
        self._warned_infeasible: set = set()
        # With an autoscaler attached, infeasible tasks park instead of
        # erroring — the fleet can grow to fit them (ray's default).
        from ray_tpu._private.runtime import get_runtime

        get_runtime().allow_pending_infeasible = True

    # -- demand/supply views ----------------------------------------------
    def _pending_demand(self) -> List[Dict[str, float]]:
        """Unschedulable resource shapes: queued tasks + pending PG bundles
        (ray: load_metrics.py pending_resource_demands)."""
        from ray_tpu._private.runtime import get_runtime

        rt = get_runtime()
        demand: List[Dict[str, float]] = []
        with rt.lock:
            for tid in rt.ready_queue:
                rec = rt.tasks.get(tid)
                if rec is not None:
                    demand.append(dict(rec.spec.resources))
            for pg_id in rt.pending_pgs:
                pg = rt.state.placement_groups.get(pg_id)
                if pg is not None and pg.state == "PENDING":
                    demand.extend(dict(b) for b in pg.bundles)
        return demand

    def _free_capacity(self) -> List[Tuple[Optional[str], Dict[str, float]]]:
        """(runtime_node_id, available) per alive node, plus the full shape
        of every still-booting launch (in-flight supply)."""
        from ray_tpu._private.runtime import get_runtime

        rt = get_runtime()
        out: List[Tuple[Optional[str], Dict[str, float]]] = [
            (n.node_id, dict(n.available)) for n in rt.state.alive_nodes()
        ]
        for pid, (tname, _ts) in self._launching.items():
            tcfg = self.config.node_types.get(tname)
            if tcfg is not None:
                out.append((None, dict(tcfg.resources)))
        return out

    def _refresh_launching(self) -> None:
        """Drop joined launches; boot-timeout stragglers are terminated."""
        now = time.monotonic()
        for pid in list(self._launching):
            tname, ts = self._launching[pid]
            if pid not in set(self.provider.non_terminated_nodes()):
                self._launching.pop(pid, None)
                continue
            if self.provider.runtime_node_id(pid) is not None:
                self._launching.pop(pid, None)
            elif now - ts > self.config.boot_timeout_s:
                # Never joined: reclaim the machine instead of leaking it.
                self.provider.terminate_node(pid)
                self._launching.pop(pid, None)

    def _nodes_by_type(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for pid in self.provider.non_terminated_nodes():
            out.setdefault(self.provider.node_type(pid), []).append(pid)
        return out

    # -- reconciliation ----------------------------------------------------
    def _launch(self, tname: str, tcfg: NodeTypeConfig, launched: Dict[str, int]):
        pid = self.provider.create_node(tname, tcfg.resources)
        self._launching[pid] = (tname, time.monotonic())
        launched[tname] = launched.get(tname, 0) + 1
        return pid

    def update(self) -> Dict[str, Any]:
        """One reconcile pass; returns {launched: {type: n},
        terminated: [id], infeasible: [shape]}."""
        launched: Dict[str, int] = {}
        infeasible: List[Dict[str, float]] = []
        self._refresh_launching()
        by_type = self._nodes_by_type()

        # 1. min_workers floors.
        for tname, tcfg in self.config.node_types.items():
            have = len(by_type.get(tname, []))
            for _ in range(max(0, tcfg.min_workers - have)):
                pid = self._launch(tname, tcfg, launched)
                by_type.setdefault(tname, []).append(pid)

        # 2. Unmet demand -> launches (first-fit-decreasing over free
        #    capacity incl. in-flight boots, then bin-pack the remainder
        #    into node types; ray: resource_demand_scheduler :103).
        free = self._free_capacity()
        reserved_nodes: set = set()  # runtime nodes absorbing queued demand
        unmet: List[Dict[str, float]] = []
        for shape in sorted(
            self._pending_demand(), key=lambda s: -sum(s.values())
        ):
            placed = False
            for nid, cap in free:
                if _fits(cap, shape):
                    _sub(cap, shape)
                    if nid is not None:
                        reserved_nodes.add(nid)
                    placed = True
                    break
            if not placed:
                unmet.append(shape)
        n_new = 0
        while unmet and n_new < self.config.max_launch_batch:
            shape = unmet[0]
            chosen: Optional[Tuple[str, NodeTypeConfig]] = None
            for tname, tcfg in sorted(
                self.config.node_types.items(),
                key=lambda kv: sum(kv[1].resources.values()),
            ):
                if len(by_type.get(tname, [])) >= tcfg.max_workers:
                    continue
                if _fits(tcfg.resources, shape):
                    chosen = (tname, tcfg)
                    break
            if chosen is None:
                unmet.pop(0)
                infeasible.append(shape)
                key = tuple(sorted(shape.items()))
                if key not in self._warned_infeasible:
                    self._warned_infeasible.add(key)
                    import warnings

                    warnings.warn(
                        f"autoscaler: demand {shape} fits NO configured node "
                        f"type (or all types at max_workers); the task will "
                        f"stay pending forever unless the config changes"
                    )
                continue
            tname, tcfg = chosen
            pid = self._launch(tname, tcfg, launched)
            by_type.setdefault(tname, []).append(pid)
            n_new += 1
            # the new node absorbs every unmet shape it fits
            cap = dict(tcfg.resources)
            unmet = [s for s in unmet if not (_fits(cap, s) and (_sub(cap, s) or True))]

        # 3. Idle terminations (above min_workers; nodes that just absorbed
        #    queued demand in step 2 are NOT idle).
        terminated = self._terminate_idle(by_type, reserved_nodes)
        return {
            "launched": launched,
            "terminated": terminated,
            "infeasible": infeasible,
        }

    def _terminate_idle(
        self, by_type: Dict[str, List[str]], reserved_nodes: set
    ) -> List[str]:
        from ray_tpu._private.runtime import get_runtime

        rt = get_runtime()
        now = time.monotonic()
        out: List[str] = []
        for tname, pids in by_type.items():
            tcfg = self.config.node_types.get(tname)
            if tcfg is None:
                continue
            killable = len(pids) - tcfg.min_workers
            for pid in pids:
                if killable <= 0:
                    break
                if pid in self._launching:
                    continue  # still booting (boot timeout reclaims these)
                nid = self.provider.runtime_node_id(pid)
                node = rt.state.nodes.get(nid) if nid else None
                # node is None here means an orphan (not booting — those are
                # skipped above — but never joined, e.g. tracker restart):
                # NOT busy, so the idle clock reclaims it.
                busy = nid in reserved_nodes or (
                    node is not None
                    and any(
                        node.available.get(k, 0.0)
                        < node.resources.get(k, 0.0) - 1e-9
                        for k in node.resources
                    )
                )
                if busy:
                    self._idle_since.pop(pid, None)
                    continue
                since = self._idle_since.setdefault(pid, now)
                if now - since >= self.config.idle_timeout_s:
                    self.provider.terminate_node(pid)
                    self._idle_since.pop(pid, None)
                    out.append(pid)
                    killable -= 1
        return out
