"""ray_tpu.autoscaler — demand-driven node fleet reconciliation.

ray: python/ray/autoscaler/ (StandardAutoscaler at
_private/autoscaler.py:168, ResourceDemandScheduler :103, NodeProvider ABC
at node_provider.py:13).  TPU-first notes: node types are host shapes
(optionally whole TPU slices via TPUPodNodeProvider); demand is read
straight from the runtime's queued tasks + pending gang bundles rather
than a separate load-metrics pipeline.
"""

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    NodeTypeConfig,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.node_provider import (
    LocalNodeProvider,
    NodeProvider,
    TPUPodNodeProvider,
)

__all__ = [
    "AutoscalerConfig",
    "LocalNodeProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "StandardAutoscaler",
    "TPUPodNodeProvider",
]
