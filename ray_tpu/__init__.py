"""ray_tpu: a TPU-native distributed ML framework.

Public API mirrors the reference's surface
(ray: python/ray/_private/worker.py -- init :1043, shutdown :1600,
 get :2263, put :2410, wait :2472, kill :2629 area, remote :2629) while the
implementation is built TPU-first (see SURVEY.md section 7): JAX/XLA programs over
device meshes do the compute; this runtime schedules host processes, owns
objects, and orchestrates multi-host SPMD.

Importing ray_tpu must stay light: no jax import happens until you touch
ray_tpu.parallel / models / train / ops.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu import exceptions
from ray_tpu._private.client import client
from ray_tpu._private.refs import ObjectRef
from ray_tpu.actor import ActorClass, ActorHandle, exit_actor, get_actor
from ray_tpu.remote_function import RemoteFunction, remote

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "exit_actor",
    "ObjectRef",
    "ActorClass",
    "ActorHandle",
    "RemoteFunction",
    "available_resources",
    "cluster_resources",
    "exceptions",
    "nodes",
]


def init(
    num_cpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    _system_config: Optional[Dict[str, Any]] = None,
    address: Optional[str] = None,
    _authkey: Optional[str] = None,
    log_to_driver: bool = True,
    **_unused,
):
    """Start the per-host runtime (driver mode), or ATTACH to a standalone
    head process when `address` is given (head-split mode — the analogue of
    ray.init(address=...) / the Ray Client ray:// attach).

    address: path to a head.json / its session dir (written by
    `python -m ray_tpu._private.head`), or "host:port" with `_authkey`.
    An attached driver can die (even kill -9) without taking the cluster
    down; detached actors keep serving and a new driver can re-attach.

    Inside a worker process this is a no-op (the worker is already connected),
    matching the reference's behavior for nested init.

    _system_config: programmatic overrides of the runtime knob table
    (ray: ray.init(_system_config=...); see _private/config.py for the
    knobs — env form is RAY_TPU_<NAME>).  Applied driver-side; workers read
    the env forms they inherit.
    """
    from ray_tpu._private import runtime as rt
    from ray_tpu._private.worker_proc import get_worker_runtime

    if get_worker_runtime() is not None:
        return
    if rt.is_initialized():
        if ignore_reinit_error:
            return
        raise RuntimeError("ray_tpu.init() called twice (pass ignore_reinit_error=True)")
    if _system_config:
        from ray_tpu._private import config as _cfg

        _cfg.set_system_config(_system_config)
    if address is not None:
        from ray_tpu._private import driver_client

        driver_client.attach(
            address, authkey=_authkey, namespace=namespace,
            log_to_driver=log_to_driver,
        )
        return
    runtime = rt.init_runtime(
        num_cpus=num_cpus, resources=resources, namespace=namespace
    )
    # Honor the flag in LOCAL driver mode too (the runtime's default comes
    # from the log_to_driver config knob).
    runtime.log_to_driver = bool(log_to_driver) and runtime.log_to_driver


def shutdown():
    from ray_tpu._private import driver_client
    from ray_tpu._private import runtime as rt

    if driver_client.is_attached():
        driver_client.detach()
        return
    rt.shutdown_runtime()


def is_initialized() -> bool:
    from ray_tpu._private import runtime as rt
    from ray_tpu._private.worker_proc import get_worker_runtime

    return rt.is_initialized() or get_worker_runtime() is not None


def _auto_init():
    from ray_tpu._private import runtime as rt
    from ray_tpu._private.worker_proc import get_worker_runtime

    if not rt.is_initialized() and get_worker_runtime() is None:
        init()


def get(refs, *, timeout: Optional[float] = None):
    _auto_init()
    return client.get(refs, timeout)


def put(value: Any) -> ObjectRef:
    _auto_init()
    return client.put(value)


def wait(refs, *, num_returns: int = 1, timeout: Optional[float] = None, fetch_local=True):
    _auto_init()
    if not isinstance(refs, list):
        raise TypeError("ray_tpu.wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return client.wait(refs, num_returns, timeout, fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    client.kill_actor(actor._id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    client.cancel(ref, force)


def available_resources() -> Dict[str, float]:
    _auto_init()
    return client.available_resources()


def cluster_resources() -> Dict[str, float]:
    _auto_init()
    return client.cluster_resources()


def nodes():
    """List cluster nodes (ray: ray.nodes())."""
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    return [
        {
            "NodeID": n.node_id,
            "Alive": n.alive,
            "Resources": dict(n.resources),
            "Available": dict(n.available),
            "IsHead": n.is_head,
        }
        for n in rt.state.nodes.values()
    ]
