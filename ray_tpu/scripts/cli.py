"""CLI: `python -m ray_tpu.scripts.cli <command>`.

ray: python/ray/scripts/scripts.py (`ray status/list/microbenchmark/
timeline/job submit`).  Commands that need a live cluster boot a local one
unless attaching is implemented by the deployment (the daemons connect to
a driver, so `status` etc. act on the CURRENT process's runtime — these
commands are most useful embedded in driver scripts or via the dashboard).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def cmd_microbenchmark(args) -> int:
    from ray_tpu._private import ray_perf

    ray_perf.main(["--json", args.json] if args.json else [])
    return 0


def _init_maybe_attached(args):
    """init() against --address (head.json path / ray:// URL) when given,
    else the local/current runtime.  Returns the attached WorkerRuntime or
    None (head-local)."""
    import ray_tpu
    from ray_tpu._private.worker_proc import get_worker_runtime

    ray_tpu.init(
        ignore_reinit_error=True,
        address=args.address if getattr(args, "address", None) else None,
    )
    return get_worker_runtime()


def _io_shard_rows(procs) -> dict:
    """Head io-shard fabric as `status` rows: one entry per shard process
    with its pushed conn-count gauge (io_shard.py metrics push)."""
    rows = {}
    for key, rec in (procs or {}).items():
        if not str(rec.get("proc", "")).startswith("io_shard"):
            continue
        internal = rec.get("internal") or {}
        rows[key] = {
            "pid": rec.get("pid"),
            "conns": int(internal.get("io_shard_conns", 0)),
            "pending_handoff_sends": int(
                internal.get("io_shard_pending_handoff_sends", 0)
            ),
            "age_s": rec.get("age_s"),
        }
    return rows


def cmd_status(args) -> int:
    import ray_tpu
    from ray_tpu.util import state as state_api

    wr = _init_maybe_attached(args)
    # Per-node elastic-capacity rows: lifecycle state (ACTIVE/DRAINING/...),
    # lease count and remote-store bytes — the drain protocol's progress is
    # readable straight off `ray_tpu status` (attached or head-local; both
    # ride the state_list "nodes" verb).
    nodes = state_api.list_nodes()
    node_rows = [
        {
            "node_id": n["node_id"],
            "state": n.get("state"),
            "is_head": n["is_head"],
            "leases": n.get("lease_count", 0),
            "store_bytes": n.get("store_bytes", 0),
            "available": n.get("available", {}),
        }
        for n in nodes
    ]
    if wr is not None:
        tele = wr.request("telemetry", None)
        out = {
            "nodes": node_rows,
            "resources": ray_tpu.cluster_resources(),
            "available": ray_tpu.available_resources(),
            "demand": state_api.demand_summary(),
            "telemetry_processes": tele.get("processes", {}),
            "telemetry": tele.get("internal", {}),
            "io_shards": _io_shard_rows(tele.get("processes")),
        }
    else:
        tele = state_api.telemetry_summary()
        out = {
            "nodes": nodes,
            "node_states": node_rows,
            "resources": ray_tpu.cluster_resources(),
            "available": ray_tpu.available_resources(),
            "demand": state_api.demand_summary(),
            "metrics": state_api.cluster_metrics(),
            "telemetry_processes": tele.get("processes", {}),
            "io_shards": _io_shard_rows(tele.get("processes")),
        }
    print(json.dumps(out, indent=1, default=str))
    return 0


def cmd_metrics(args) -> int:
    """`ray_tpu metrics`: the pushed-metrics plane — cluster aggregate +
    per-process snapshot ages; --series <name> dumps that aggregate's
    ring time series (the bounded GCS-side storage)."""
    from ray_tpu.util import state as state_api

    wr = _init_maybe_attached(args)
    if args.series:
        if wr is not None:
            out = wr.request("telemetry_series", args.series)
        else:
            out = state_api.telemetry_series(args.series)
    elif wr is not None:
        out = wr.request("telemetry", None)
    else:
        out = state_api.telemetry_summary()
    print(json.dumps(out, indent=1, default=str))
    return 0


def cmd_memory(args) -> int:
    """`ray_tpu memory`: the cluster object ledger — per-node bytes, top
    objects with holder attribution, leak suspects (`--leaks`), group-by
    node|owner|callsite (ray: `ray memory`).  Attachable: --address gets
    the head's join over the request plane."""
    from ray_tpu.util import state as state_api

    _init_maybe_attached(args)
    out = state_api.memory_summary(
        group_by=args.group_by, top=args.top, include_events=args.events
    )
    if args.leaks:
        out = {
            "leak_suspects": out["leak_suspects"],
            "leak_suspect_bytes": out["leak_suspect_bytes"],
            "leaks": [
                {
                    "object_id": r["object_id"],
                    "size_bytes": r["size_bytes"],
                    "location": r["location"],
                    "reason": r["leak"],
                    "holders": [
                        {
                            "holder": h["holder"],
                            "node": h["node"],
                            "pid": h["pid"],
                            "count": h["count"],
                            "dead": h["dead"],
                        }
                        for h in r["holders"]
                    ],
                    "age_s": r["age_s"],
                }
                for r in out["leaks"]
            ],
        }
    print(json.dumps(out, indent=1, default=str))
    return 0


def cmd_timeline(args) -> int:
    from ray_tpu.dashboard import timeline

    wr = _init_maybe_attached(args)
    out = args.output or "timeline.json"
    window = {"last": args.last, "since": args.since}
    if wr is not None:
        events = wr.request("timeline", window)
    else:
        events = timeline(**window)
    with open(out, "w") as f:
        json.dump(events, f)
    pids = {e.get("pid") for e in events}
    bound = (
        f" (window: --since {args.since})" if args.since
        else f" (window: last {args.last}s)" if args.last
        else ""
    )
    print(
        f"wrote {out}: {len(events)} events across {len(pids)} processes"
        f"{bound} (open in chrome://tracing or Perfetto)"
    )
    return 0


def cmd_profile(args) -> int:
    """`ray_tpu profile`: cluster-wide sampling flamegraph — broadcast
    start, sample for --seconds, broadcast stop, merge every process's
    pushed collapsed-stack table (+ the head's own), write --flame
    out.txt (collapsed) or out.svg (self-contained flamegraph)."""
    import time as _time

    from ray_tpu._private import profiler as _profiler
    from ray_tpu.util import state as state_api

    _init_maybe_attached(args)
    started = state_api.profile_start(hz=args.hz)
    _time.sleep(max(args.seconds, 0.1))
    state_api.profile_stop()
    # One ticker beat so the workers' final prof_push oneways land.
    _time.sleep(0.7)
    report = state_api.profile_report(node=args.node, pid=args.pid)
    samples = report.get("samples") or {}
    if args.flame:
        if args.flame.endswith(".svg"):
            body = _profiler.flamegraph_svg(
                samples, title=f"ray_tpu profile ({args.seconds}s "
                f"@ {started.get('hz')}Hz)"
            )
        else:
            body = _profiler.folded_text(samples)
        with open(args.flame, "w") as f:
            f.write(body)
        print(f"wrote {args.flame}: {len(samples)} stacks")
    top = sorted(samples.items(), key=lambda kv: -kv[1])[: args.top]
    print(
        json.dumps(
            {
                "hz": started.get("hz"),
                "seconds": args.seconds,
                "total_samples": report.get("total_samples"),
                "pids": report.get("pids"),
                "processes": report.get("processes"),
                "top_stacks": [{"stack": s, "samples": n} for s, n in top],
            },
            indent=1,
            default=str,
        )
    )
    return 0


def cmd_tasks(args) -> int:
    """`ray_tpu tasks`: per-task lifecycle attribution — stage-duration
    percentiles, accounted fraction, the --slow N slowest tasks with
    their per-stage breakdown + critical stage, and live tasks with the
    stage each is stuck in."""
    from ray_tpu.util import state as state_api

    _init_maybe_attached(args)
    out = state_api.task_summary(slow=args.slow)
    if args.summary:
        out = {
            k: out[k]
            for k in (
                "tasks", "states", "stages", "wall_s_total",
                "accounted_s_total", "accounted_fraction",
            )
            if k in out
        }
    print(json.dumps(out, indent=1, default=str))
    return 0


def cmd_job_submit(args) -> int:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=" ".join(args.entrypoint))
    status = client.wait_until_finish(job_id, timeout=args.timeout)
    sys.stdout.write(client.get_job_logs(job_id))
    print(f"\njob {job_id}: {status}")
    return 0 if status == "SUCCEEDED" else 1


def cmd_logs(args) -> int:
    """Dump a worker's captured stdout/stderr lines (ray: `ray logs`).
    With --actor, resolve the named actor's current worker first; with
    --all, aggregate the tail across EVERY worker with node/pid line
    prefixes (attachable — reuses the head request plane)."""
    import ray_tpu
    from ray_tpu._private.worker_proc import get_worker_runtime

    ray_tpu.init(
        ignore_reinit_error=True,
        address=args.address if getattr(args, "address", None) else None,
    )
    if args.all:
        wr = get_worker_runtime()
        if wr is not None:  # attached driver: ask the head
            per_worker = wr.request("get_logs_all", args.tail or None)
        else:
            from ray_tpu._private.runtime import get_runtime

            per_worker = get_runtime().get_logs_all(args.tail or None)
        for wid in sorted(per_worker):
            rec = per_worker[wid]
            prefix = f"[{rec.get('node') or '?'}/{rec.get('pid') or wid}]"
            for line in rec["lines"]:
                sys.stdout.write(f"{prefix} {line}\n")
        return 0
    wid = args.worker
    if args.actor:
        from ray_tpu._private.runtime import get_runtime

        wr = get_worker_runtime()
        if wr is not None:
            raise SystemExit("--actor lookup requires a head-local driver")
        rt = get_runtime()
        info = rt.state.get_named_actor(args.actor, rt.namespace)
        if info is None or not info.worker_id:
            raise SystemExit(f"no live worker for actor {args.actor!r}")
        wid = info.worker_id
    wr = get_worker_runtime()
    if wr is not None:  # attached driver: ask the head
        lines = wr.request("get_logs", (wid, args.tail))
    else:
        from ray_tpu._private.runtime import get_runtime

        lines = get_runtime().get_logs(wid, args.tail)
    sys.stdout.write("\n".join(lines) + ("\n" if lines else ""))
    return 0


def _live_head_pid(session_dir: str):
    """pid from head.pid if it plausibly IS a live head.  Returns
    (pid, known): known=False when liveness can't be verified (no /proc,
    e.g. macOS) — callers must then treat the pid as possibly-live rather
    than stale."""
    try:
        with open(os.path.join(session_dir, "head.pid")) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return None, True
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return None, True
    except PermissionError:
        pass  # alive, owned by someone else
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            is_head = b"ray_tpu._private.head" in f.read()
        return (pid if is_head else None), True
    except OSError:
        # /proc unavailable: the pid is alive but unverifiable.
        return pid, False


def cmd_start(args) -> int:
    """`ray_tpu start --head`: boot a standalone head process (ray: `ray
    start --head`).  Prints the head.json path + the ray:// address a
    remote driver passes to init()."""
    from ray_tpu._private.head import launch_head_subprocess

    if not args.head:
        print(
            "only --head is supported here; on worker hosts launch "
            "`python -m ray_tpu._private.node_daemon` pointed at the head "
            "(env RAY_TPU_DRIVER_HOST/PORT/AUTHKEY, RAY_TPU_NODE_CONFIG)",
            file=sys.stderr,
        )
        return 2
    session_dir = args.session_dir or os.path.join(
        "/tmp", f"raytpu-session-{os.getpid()}"
    )
    os.makedirs(session_dir, exist_ok=True)
    pid, _known = _live_head_pid(session_dir)
    if pid is not None:
        # Matching `ray start`'s already-running refusal: a second head
        # would overwrite head.pid/head.json and orphan the first.
        print(
            f"a head (pid {pid}) is already running for {session_dir}; "
            "run `ray_tpu stop` first or pick another --session-dir",
            file=sys.stderr,
        )
        return 1
    proc, head_json = launch_head_subprocess(
        session_dir, num_cpus=args.num_cpus, session=args.session, detach=True
    )
    with open(head_json) as f:
        info = json.load(f)
    # Record the head pid so `ray_tpu stop` can find it.
    with open(os.path.join(session_dir, "head.pid"), "w") as f:
        f.write(str(proc.pid))
    print(f"head started (pid {proc.pid})")
    print(f"  head.json: {head_json}")
    print(f"  attach:    ray_tpu.init(address={head_json!r})")
    print(
        f"  remote:    ray_tpu.init(address='ray://{info['host']}:"
        f"{info['port']}', _authkey={info['authkey']!r})"
    )
    return 0


def cmd_stop(args) -> int:
    """`ray_tpu stop`: terminate the head started by `ray_tpu start`."""
    import signal as _signal

    pid_file = os.path.join(args.session_dir, "head.pid")
    if not os.path.exists(pid_file):
        print(f"no head.pid under {args.session_dir}", file=sys.stderr)
        return 1
    # Stale-pid guard: after a crash/reboot the OS may have reused the pid
    # for an unrelated process — only SIGTERM on a POSITIVE head match;
    # when liveness can't be verified (no /proc) err toward killing the
    # recorded pid rather than stranding a live head.
    pid, known = _live_head_pid(args.session_dir)
    if pid is None:
        try:
            os.unlink(pid_file)
        except OSError:
            pass
        print("head already gone (stale head.pid removed)")
        return 0
    if not known:
        print(f"cannot verify pid {pid} is a head (no /proc); stopping it anyway")
    try:
        os.kill(pid, _signal.SIGTERM)
    except ProcessLookupError:
        pass
    try:
        os.unlink(pid_file)
    except OSError:
        pass
    print(f"sent SIGTERM to head pid {pid}")
    return 0


def cmd_bench(args) -> int:
    import os
    import subprocess

    import ray_tpu

    bench = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__))),
        "bench.py",
    )
    return subprocess.call([sys.executable, bench])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    mb = sub.add_parser("microbenchmark", help="core runtime microbenchmarks")
    mb.add_argument("--json", help="write results to this file")
    mb.set_defaults(fn=cmd_microbenchmark)

    st = sub.add_parser("status", help="cluster nodes/resources/metrics")
    st.add_argument("--address", help="head.json path or ray:// URL (attached mode)")
    st.set_defaults(fn=cmd_status)

    me = sub.add_parser(
        "metrics", help="pushed-metrics plane: aggregate + per-process ages"
    )
    me.add_argument("--series", help="dump one aggregate's ring time series")
    me.add_argument("--address", help="head.json path or ray:// URL (attached mode)")
    me.set_defaults(fn=cmd_metrics)

    mm = sub.add_parser(
        "memory", help="cluster object ledger: bytes, holders, leak suspects"
    )
    mm.add_argument(
        "--group-by", choices=("node", "owner", "callsite"), default=None
    )
    mm.add_argument(
        "--leaks", action="store_true",
        help="only leak suspects, with holder node/pid attribution",
    )
    mm.add_argument("--top", type=int, default=20, help="top-N objects by size")
    mm.add_argument(
        "--events", action="store_true",
        help="include the recent object lifecycle event ring",
    )
    mm.add_argument("--address", help="head.json path or ray:// URL (attached mode)")
    mm.set_defaults(fn=cmd_memory)

    tl = sub.add_parser(
        "timeline", help="export the merged chrome-trace cluster timeline"
    )
    tl.add_argument("--output", "-o")
    tl.add_argument(
        "--last", type=float, default=None, metavar="SECONDS",
        help="only events from the trailing window (bounded export)",
    )
    tl.add_argument(
        "--since", type=float, default=None, metavar="TS",
        help="only events ending at/after this epoch timestamp",
    )
    tl.add_argument("--address", help="head.json path or ray:// URL (attached mode)")
    tl.set_defaults(fn=cmd_timeline)

    pf = sub.add_parser(
        "profile", help="cluster-wide sampling flamegraph (profiler.py)"
    )
    pf.add_argument(
        "--seconds", type=float, default=5.0, help="sampling window"
    )
    pf.add_argument(
        "--hz", type=float, default=None,
        help="sampling rate (default: profiler.DEFAULT_HZ)",
    )
    pf.add_argument("--node", help="filter the merge to one node id")
    pf.add_argument("--pid", type=int, help="filter the merge to one pid")
    pf.add_argument(
        "--flame", metavar="OUT",
        help="write the merged flamegraph: *.txt = collapsed stacks, "
        "*.svg = self-contained flamegraph",
    )
    pf.add_argument("--top", type=int, default=15, help="top stacks printed")
    pf.add_argument("--address", help="head.json path or ray:// URL (attached mode)")
    pf.set_defaults(fn=cmd_profile)

    tk = sub.add_parser(
        "tasks", help="per-task lifecycle attribution (stage durations)"
    )
    tk.add_argument(
        "--slow", type=int, default=10, help="N slowest tasks listed"
    )
    tk.add_argument(
        "--summary", action="store_true",
        help="aggregate stage stats only (no per-task rows)",
    )
    tk.add_argument("--address", help="head.json path or ray:// URL (attached mode)")
    tk.set_defaults(fn=cmd_tasks)

    js = sub.add_parser("job", help="submit a job and stream its logs")
    js.add_argument("entrypoint", nargs="+")
    js.add_argument("--timeout", type=float, default=3600.0)
    js.set_defaults(fn=cmd_job_submit)

    lg = sub.add_parser("logs", help="dump a worker's captured output")
    lg.add_argument("worker", nargs="?", help="worker id")
    lg.add_argument("--actor", help="named actor: dump its worker's logs")
    lg.add_argument(
        "--all", action="store_true",
        help="aggregate tail across every worker, node/pid-prefixed",
    )
    lg.add_argument("--tail", type=int, default=0, help="last N lines only")
    lg.add_argument("--address", help="head.json path (attached mode)")
    lg.set_defaults(fn=cmd_logs)

    be = sub.add_parser("bench", help="run the train benchmark (bench.py)")
    be.set_defaults(fn=cmd_bench)

    sta = sub.add_parser("start", help="start a standalone head process")
    sta.add_argument("--head", action="store_true")
    sta.add_argument("--num-cpus", type=int, default=4)
    sta.add_argument("--session-dir")
    sta.add_argument("--session")
    sta.set_defaults(fn=cmd_start)

    sto = sub.add_parser("stop", help="stop the head started by `start`")
    sto.add_argument("--session-dir", required=True)
    sto.set_defaults(fn=cmd_stop)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
