"""ray_tpu.parallel: meshes, sharding rules, and collectives.

TPU-native replacement for the reference's NCCL/GLOO collective layer
(python/ray/util/collective/) and torch process-group plumbing
(python/ray/train/torch/config.py) — see SURVEY.md §5.8.
"""

from ray_tpu.parallel.bootstrap import MeshBootstrap, pick_coordinator_address, setup_mesh
from ray_tpu.parallel.collectives import (
    CollectiveGroup,
    allgather,
    allreduce,
    barrier,
    broadcast,
    destroy_collective_group,
    device_allreduce,
    get_group,
    init_collective_group,
    recv,
    reducescatter,
    send,
)
from ray_tpu.parallel.pipeline import pipeline_apply, pipeline_train_step_1f1b
from ray_tpu.parallel.mesh import (
    AXIS_ORDER,
    MeshSpec,
    build_mesh,
    mesh_axis_sizes,
    remesh_spec,
    single_device_mesh,
)
from ray_tpu.parallel.sharding import (
    PRESETS,
    Rules,
    logical_to_spec,
    resolve_rules,
    tree_shardings,
    with_logical_constraint,
)

__all__ = [
    "AXIS_ORDER",
    "MeshSpec",
    "MeshBootstrap",
    "CollectiveGroup",
    "PRESETS",
    "Rules",
    "allgather",
    "allreduce",
    "barrier",
    "broadcast",
    "build_mesh",
    "destroy_collective_group",
    "device_allreduce",
    "get_group",
    "init_collective_group",
    "logical_to_spec",
    "mesh_axis_sizes",
    "pipeline_apply",
    "pipeline_train_step_1f1b",
    "pick_coordinator_address",
    "recv",
    "reducescatter",
    "remesh_spec",
    "resolve_rules",
    "send",
    "setup_mesh",
    "single_device_mesh",
    "tree_shardings",
    "with_logical_constraint",
]
