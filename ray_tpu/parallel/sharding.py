"""Logical-axis sharding rules: DP/FSDP/TP/SP/EP as data, not wrappers.

The reference expresses parallelism strategy as *wrapper choice* —
DistributedDataParallel vs FullyShardedDataParallel selected by a string
(ray: python/ray/train/torch/train_loop_utils.py:92-98).  TPU-native, a
strategy is just a table mapping logical array axes ("embed", "mlp", "heads",
"batch", ...) to mesh axes; XLA inserts the collectives.  Switching DP → FSDP
→ TP → 3D is a rules change, no model code change.
"""

from __future__ import annotations

import math

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxis = Union[None, str, Tuple[str, ...]]
Rules = Dict[str, MeshAxis]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """jax.shard_map across jax versions: the top-level export (and its
    `check_vma` kwarg) only exist from jax 0.6; older jax has
    jax.experimental.shard_map with the same semantics under `check_rep`."""
    try:
        from jax import shard_map as _shard_map

        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

# Logical axis vocabulary used by models/ (see models/transformer.py).
# Parameter axes and activation axes are distinct namespaces (act_*): under
# FSDP, params shard their embed dim over `fsdp` while activations shard
# batch over ("data", "fsdp") — same mesh axis, different logical axes, so
# a single rules table can't alias them.
#
#   embed/heads/kv_heads/head_dim/mlp/vocab/expert — parameter dims
#   layers — scan-over-layers leading axis (sharded over `pipeline` by
#            pp_rules; unsharded elsewhere)
#   act_batch/act_seq/act_embed/act_heads/act_kv_heads/act_head_dim/
#   act_mlp/act_vocab — activation dims

_BASE: Rules = {
    # params
    "embed": None,
    "heads": None,
    "kv_heads": None,
    "head_dim": None,
    "mlp": None,
    "vocab": None,
    "expert": None,
    "layers": None,
    # activations
    "act_batch": ("data", "fsdp"),
    "act_seq": None,
    "act_embed": None,
    "act_heads": None,
    "act_kv_heads": None,
    "act_head_dim": None,
    "act_mlp": None,
    "act_vocab": None,
    "act_expert": None,
}


def dp_rules() -> Rules:
    """Pure data parallel: replicate params, shard batch."""
    return dict(_BASE)


def fsdp_rules() -> Rules:
    """ZeRO-3 analogue: shard every large param dim over the fsdp axis.

    XLA all-gathers params per layer and reduce-scatters grads — the compiled
    equivalent of the reference's FSDP wrapper (train_loop_utils.py:92-98).
    """
    r = dict(_BASE)
    r.update(embed="fsdp", mlp=None, vocab=None)
    return r


def tp_rules() -> Rules:
    """Megatron-style tensor parallel over the tensor axis (absent in the
    reference — SURVEY.md §2.4 lists TP as not built-in)."""
    r = dict(_BASE)
    r.update(
        heads="tensor", kv_heads="tensor", mlp="tensor", vocab="tensor",
        act_heads="tensor", act_kv_heads="tensor", act_mlp="tensor",
        act_vocab="tensor",
    )
    return r


def fsdp_tp_rules() -> Rules:
    """2D: params sharded over fsdp × tensor (the standard pod recipe)."""
    r = tp_rules()
    r.update(embed="fsdp")
    return r


def sp_rules() -> Rules:
    """Context/sequence parallel: shard activations along seq (ring attention
    pairs with this — ops/ring_attention.py)."""
    r = fsdp_tp_rules()
    r.update(act_seq="seq")
    return r


def pp_rules() -> Rules:
    """Pipeline parallel: the scan-over-layers param stack shards over the
    `pipeline` axis — each pipeline-stage device holds L/P layers, and the
    model dispatches the GPipe microbatch schedule
    (parallel/pipeline.py) instead of a plain layer scan."""
    r = dict(_BASE)
    r.update(layers="pipeline")
    return r


def pp_fsdp_rules() -> Rules:
    """Pipeline x FSDP: layer stack over `pipeline`, params-at-rest sharded
    over `fsdp` WITHIN each stage (all-gathered per stage per step, grads
    reduce-scattered back — ZeRO-style optimizer-state sharding on top of
    the GPipe schedule; parallel/pipeline.py fsdp_dims)."""
    r = dict(_BASE)
    r.update(layers="pipeline", embed="fsdp")
    return r


def ep_rules() -> Rules:
    """Expert parallel for MoE layers."""
    r = fsdp_tp_rules()
    r.update(expert="expert", act_expert="expert")
    return r


PRESETS = {
    "dp": dp_rules,
    "fsdp": fsdp_rules,
    "tp": tp_rules,
    "fsdp_tp": fsdp_tp_rules,
    "sp": sp_rules,
    "pp": pp_rules,
    "pp_fsdp": pp_fsdp_rules,
    "ep": ep_rules,
}


def resolve_rules(strategy: Union[str, Rules]) -> Rules:
    if isinstance(strategy, str):
        try:
            return PRESETS[strategy]()
        except KeyError:
            raise ValueError(f"unknown strategy {strategy!r}; options {sorted(PRESETS)}")
    return dict(strategy)


def logical_to_spec(logical_axes: Sequence[Optional[str]], rules: Rules) -> P:
    """Map a tuple of logical axis names (None = unsharded) to a PartitionSpec."""
    return P(*(rules.get(a) if a is not None else None for a in logical_axes))


def tree_shardings(logical_tree, rules: Rules, mesh: Mesh):
    """Map a pytree of logical-axes tuples to a pytree of NamedShardings."""
    return jax.tree_util.tree_map(
        lambda axes: NamedSharding(mesh, logical_to_spec(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        ),
    )


def _fit_spec(shape, spec: P, mesh: Mesh) -> P:
    """Drop mesh axes (innermost first) from any spec entry whose axis-size
    product does not divide the corresponding dim. Replicates instead of
    erroring for e.g. GQA kv_heads < tensor-axis size."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    entries = list(spec) + [None] * (len(shape) - len(spec))
    new_entries = []
    for dim, entry in zip(shape, entries):
        if entry is None:
            new_entries.append(None)
            continue
        axes = list(entry) if isinstance(entry, tuple) else [entry]
        while axes and dim % math.prod(sizes[a] for a in axes) != 0:
            axes.pop()
        new_entries.append(tuple(axes) if len(axes) > 1 else (axes[0] if axes else None))
    return P(*new_entries)


def fit_shardings(shape_tree, sharding_tree):
    """Shape-validate a sharding tree (see _fit_spec)."""

    def fit_one(shape_leaf, sharding: NamedSharding) -> NamedSharding:
        shape = getattr(shape_leaf, "shape", shape_leaf)
        return NamedSharding(sharding.mesh, _fit_spec(shape, sharding.spec, sharding.mesh))

    return jax.tree_util.tree_map(
        fit_one, shape_tree, sharding_tree,
        is_leaf=lambda x: hasattr(x, "shape"),
    )


def with_logical_constraint(
    x,
    logical_axes: Sequence[Optional[str]],
    rules: Rules,
    mesh: Optional[Mesh] = None,
):
    """Activation sharding hint inside jit (lax.with_sharding_constraint).

    With an explicit mesh the constraint is a shape-fitted NamedSharding
    (axes that don't divide the dim are dropped, matching fit_shardings);
    otherwise a bare PartitionSpec relying on the enclosing `with mesh:`
    scope.
    """
    spec = logical_to_spec(logical_axes, rules)
    if mesh is not None:
        spec = _fit_spec(x.shape, spec, mesh)
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)
