"""Host-side collective API with the reference's surface, TPU-native semantics.

The reference's ray.util.collective (python/ray/util/collective/collective.py:
120 init_collective_group, :258 allreduce, :423 allgather, :531/:594 send/recv)
wraps NCCL/GLOO runtime libraries.  Here:

- DEVICE arrays: collectives are *compiled* — use `psum/pmean/all_gather/
  ppermute` inside shard_map/pjit (see device_allreduce below for the
  shard_map-wrapped form).  There is nothing to "initialize".
- HOST arrays (control data, rendezvous, metric reduction across actor
  groups): a lightweight actor-backed group mirrors the GLOO path, implemented
  over the ray_tpu runtime itself.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "prod": lambda xs: np.prod(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
    "mean": lambda xs: np.mean(xs, axis=0),
}


@ray_tpu.remote(num_cpus=0)
class _GroupCoordinator:
    """Named rendezvous actor holding per-collective state.

    Plays the role of the reference's NCCLUniqueID store actor
    (python/ray/util/collective/collective.py:40 GroupManager) — but since XLA
    needs no communicator handshake, it doubles as the data plane for host
    arrays (fine for control-sized payloads; tensor traffic is ICI-compiled).

    All waits are ASYNC parks on the actor's event loop (one RPC per rank
    per collective, zero polling): the last contributor sets the round's
    asyncio.Event and every parked rank resumes — the blocking analogue of
    the reference's gloo rendezvous, built on the runtime's async actors.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._rounds: Dict[str, Dict[int, Any]] = {}
        self._done: Dict[str, Any] = {}
        self._collected: Dict[str, set] = {}
        self._events: Dict[str, Any] = {}
        # p2p keys whose receiver timed out and left: a LATE put for one
        # of these is dropped instead of stranding the payload forever
        # (p2p seqs are never reused).  Bounded: entries clear on the
        # matching put; a dead sender leaves only the key string.
        self._abandoned: "set[str]" = set()

    def _event(self, key: str):
        import asyncio

        ev = self._events.get(key)
        if ev is None:
            ev = self._events[key] = asyncio.Event()
        return ev

    async def exchange(self, key: str, rank: int, value, timeout: float):
        """Contribute this rank's value and WAIT (parked, not polling)
        until every rank has; returns the full {rank: value} round."""
        import asyncio

        round_ = self._rounds.setdefault(key, {})
        round_[rank] = value
        ev = self._event(key)
        if len(round_) == self.world_size:
            self._done[key] = dict(round_)
            del self._rounds[key]
            ev.set()
        else:
            try:
                await asyncio.wait_for(ev.wait(), timeout)
            except asyncio.TimeoutError:
                if key not in self._done:
                    # True timeout: withdraw this rank's contribution so a
                    # retried round sees no ghost participant, and free the
                    # round's state once the last waiter leaves — timed-out
                    # keys are never reused (seq-suffixed) and would leak.
                    round_ = self._rounds.get(key)
                    if round_ is not None:
                        round_.pop(rank, None)
                        if not round_:
                            del self._rounds[key]
                            self._events.pop(key, None)
                    return None
                # Lost the race: the round completed as the timer fired —
                # collect normally (skipping would strand _done forever).
        out = self._done.get(key)
        # Free the round once every rank has fetched it, so a long-running
        # loop of collectives doesn't grow the coordinator without bound.
        seen = self._collected.setdefault(key, set())
        seen.add(rank)
        if len(seen) == self.world_size:
            self._done.pop(key, None)
            self._collected.pop(key, None)
            self._events.pop(key, None)
        return out

    async def p2p_put(self, key: str, value):
        if key in self._abandoned:
            self._abandoned.discard(key)
            self._events.pop(key, None)
            return  # receiver already gave up on this seq: drop, don't strand
        self._done[key] = value
        self._event(key).set()

    async def p2p_take(self, key: str, timeout: float):
        import asyncio

        ev = self._event(key)
        if key not in self._done:
            try:
                await asyncio.wait_for(ev.wait(), timeout)
            except asyncio.TimeoutError:
                if key not in self._done:
                    # True timeout (not the completion-vs-timer race —
                    # that falls through and drains normally).
                    self._events.pop(key, None)
                    self._abandoned.add(key)
                    return None
        self._events.pop(key, None)
        return self._done.pop(key, None)


class CollectiveGroup:
    """One rank's view of a host collective group.

    timeout_s bounds every collective: if a peer rank dies before
    contributing, the others raise instead of waiting forever (the
    reference's collective ops error out on dead peers).  Waits park on
    the coordinator's event loop — one RPC per rank per collective, no
    client-side polling.
    """

    def __init__(self, name: str, world_size: int, rank: int, timeout_s: float = 120.0):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.timeout_s = timeout_s
        self._seq = 0
        self._p2p_seq: Dict[tuple, int] = {}  # (src, dst) -> next seq
        self._coord = _get_or_create_coordinator(name, world_size)

    def _timeout_error(self, what: str) -> RuntimeError:
        return RuntimeError(
            f"collective {what} timed out after {self.timeout_s}s in group "
            f"{self.name!r} (rank {self.rank}/{self.world_size}) — a peer "
            "rank likely died before contributing"
        )

    # -- collectives ------------------------------------------------------
    def _exchange(self, tag: str, value) -> Dict[int, Any]:
        self._seq += 1
        key = f"{tag}:{self._seq}"
        out = ray_tpu.get(
            self._coord.exchange.remote(key, self.rank, value, self.timeout_s),
            timeout=self.timeout_s + 30,
        )
        if out is None:
            raise self._timeout_error(key)
        return out

    def allreduce(self, arr, op: str = "sum"):
        parts = self._exchange("ar", np.asarray(arr))
        return _REDUCE_OPS[op]([parts[r] for r in sorted(parts)])

    def allgather(self, arr) -> List[np.ndarray]:
        parts = self._exchange("ag", np.asarray(arr))
        return [parts[r] for r in sorted(parts)]

    def reducescatter(self, arr, op: str = "sum"):
        reduced = self.allreduce(arr, op)
        return np.array_split(reduced, self.world_size)[self.rank]

    def broadcast(self, arr, src_rank: int = 0):
        parts = self._exchange("bc", np.asarray(arr) if self.rank == src_rank else None)
        return parts[src_rank]

    def barrier(self):
        self._exchange("bar", None)

    def _p2p_key(self, src: int, dst: int) -> str:
        # Sequence numbers are per (src, dst) channel: a shared counter would
        # desynchronize keys under any asymmetric send/recv pattern.
        seq = self._p2p_seq.get((src, dst), 0)
        self._p2p_seq[(src, dst)] = seq + 1
        return f"p2p:{src}->{dst}:{seq}"

    def send(self, arr, dst_rank: int):
        key = self._p2p_key(self.rank, dst_rank)
        ray_tpu.get(self._coord.p2p_put.remote(key, np.asarray(arr)))

    def recv(self, src_rank: int):
        key = self._p2p_key(src_rank, self.rank)
        out = ray_tpu.get(
            self._coord.p2p_take.remote(key, self.timeout_s),
            timeout=self.timeout_s + 30,
        )
        if out is None:
            raise self._timeout_error(key)
        return out


_registry: Dict[str, "CollectiveGroup"] = {}
_groups_lock = threading.Lock()


def _get_or_create_coordinator(name: str, world_size: int):
    """Racy rendezvous: every rank tries get-then-create; exactly one create
    wins the name registration, losers fall back to get (mirrors the
    reference's named-actor NCCL-ID rendezvous, collective.py:40)."""
    import time

    actor_name = f"_collective_coord:{name}"
    deadline = time.monotonic() + 30
    while True:
        try:
            return ray_tpu.get_actor(actor_name)
        except Exception:
            pass
        try:
            return _GroupCoordinator.options(name=actor_name).remote(world_size)
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.01)


def init_collective_group(
    world_size: int, rank: int, backend: str = "xla", group_name: str = "default"
) -> CollectiveGroup:
    """ray: util/collective/collective.py:120. backend is accepted for API
    parity; host groups always run over the actor runtime ("gloo" analogue),
    device collectives are always compiled XLA."""
    group = CollectiveGroup(group_name, world_size, rank)
    _groups()[group_name] = group
    return group


def _groups() -> Dict[str, CollectiveGroup]:
    return _registry


def get_group(group_name: str = "default") -> CollectiveGroup:
    try:
        return _groups()[group_name]
    except KeyError:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this process"
        )


def allreduce(arr, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(arr, op)


def allgather(arr, group_name: str = "default"):
    return get_group(group_name).allgather(arr)


def reducescatter(arr, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).reducescatter(arr, op)


def broadcast(arr, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(arr, src_rank)


def barrier(group_name: str = "default"):
    get_group(group_name).barrier()


def send(arr, dst_rank: int, group_name: str = "default"):
    get_group(group_name).send(arr, dst_rank)


def recv(src_rank: int, group_name: str = "default"):
    return get_group(group_name).recv(src_rank)


def destroy_collective_group(group_name: str = "default"):
    _groups().pop(group_name, None)


# -- device-side (compiled) collectives ----------------------------------


_device_allreduce_cache: Dict[tuple, Any] = {}


def device_allreduce(x, mesh, axis: str = "data", op: str = "sum"):
    """Compiled all-reduce over a mesh axis via shard_map — the ICI path.

    This is what replaces NCCLGroup.allreduce (nccl_collective_group.py:175):
    the collective is part of the XLA program, not a runtime call.  Compiled
    programs are cached per (mesh, axis, op) so repeated calls don't retrace.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.sharding import shard_map

    key = (mesh, axis, op)
    run = _device_allreduce_cache.get(key)
    if run is None:
        reducer = {
            "sum": jax.lax.psum,
            "mean": jax.lax.pmean,
            "max": jax.lax.pmax,
            "min": jax.lax.pmin,
        }[op]

        @jax.jit
        def run(v):
            return shard_map(
                lambda s: reducer(s, axis),
                mesh=mesh,
                in_specs=P(axis),
                out_specs=P(),
            )(v)

        _device_allreduce_cache[key] = run
    return run(x)
