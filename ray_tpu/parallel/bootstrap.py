"""Mesh bootstrap: get N host actors into one multi-host XLA computation.

The reference's analogue is torch process-group setup driven by Ray Train
(_setup_torch_process_group, python/ray/train/torch/config.py:69 — rank-0
address broadcast over actor RPC, then dist.init_process_group :113).  The
TPU-native version: rank 0 of a worker group publishes a coordinator address;
every host calls jax.distributed.initialize(coordinator, num_processes,
process_id); after that, jax.devices() spans the whole slice and a single
pjit'ed program runs SPMD across hosts with ICI collectives compiled in.

On a single host (or CPU-virtual-device testing) initialize() is skipped and
the local devices already form the full mesh.
"""

from __future__ import annotations

import dataclasses
import os
import socket
from typing import Optional

from ray_tpu.parallel.mesh import MeshSpec, build_mesh


@dataclasses.dataclass
class MeshBootstrap:
    """Per-process description of how to join the global mesh."""

    num_processes: int = 1
    process_id: int = 0
    coordinator_address: Optional[str] = None  # "host:port", required if >1 proc
    local_device_ids: Optional[list] = None

    def initialize(self):
        """Join the multi-host XLA runtime. Idempotent; no-op single-process."""
        if self.num_processes <= 1:
            return
        import jax

        jax.distributed.initialize(
            coordinator_address=self.coordinator_address,
            num_processes=self.num_processes,
            process_id=self.process_id,
            local_device_ids=self.local_device_ids,
        )

    def shutdown(self):
        """Leave the multi-host XLA runtime so this process can rejoin a
        re-meshed gang (elastic SPMD: the coordinator and world size change
        when the group reforms at N-1 or scales back up).  Safe to call
        when initialize() never ran or the runtime is already down."""
        if self.num_processes <= 1:
            return
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            pass  # never initialized / coordinator already gone


def pick_coordinator_address(port: int = 0) -> str:
    """Choose a reachable coordinator address on this host (rank-0 side)."""
    host = os.environ.get("RAY_TPU_HOST_IP") or socket.gethostbyname(socket.gethostname())
    if port == 0:
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
    return f"{host}:{port}"


def setup_mesh(
    spec: Optional[MeshSpec] = None,
    bootstrap: Optional[MeshBootstrap] = None,
):
    """Initialize (maybe multi-host) XLA and build the mesh. The worker-group
    entry point used by train/backend_jax.py."""
    if bootstrap is not None:
        bootstrap.initialize()
    return build_mesh(spec)
