"""Pipeline parallelism: GPipe-style microbatch pipelining over the
`pipeline` mesh axis.

Absent from the reference entirely (SURVEY §2.4: PP not built in) — built
TPU-first: each pipeline-axis device holds ONE stage's parameters;
microbatches stream through the stages with `ppermute` hops over ICI, and
the whole schedule is a single `lax.scan` inside `shard_map`, so XLA
overlaps each stage's matmuls with its neighbor transfers and reverse-mode
AD differentiates straight through the schedule (backward pipeline for
free — ppermute's transpose is the reverse ring).

Composes with the other axes: the batch dim shards over ("data", "fsdp")
as usual; stages over "pipeline".
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _gather_params(params, gather_dims):
    """all_gather the fsdp-sharded leaves (see _pipeline_body docstring).
    gather_dims leaves are (dim_index, mesh_axis) tuples or None."""
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(gather_dims)
    gathered = [
        p if gd is None else jax.lax.all_gather(p, gd[1], axis=gd[0], tiled=True)
        for p, gd in zip(flat_p, flat_g)
    ]
    return jax.tree_util.tree_unflatten(treedef, gathered)


def _pipeline_body(
    stage_params,
    x: jax.Array,
    *,
    fn: Callable,
    n_microbatches: int,
    axis: str,
    gather_dims=None,
):
    """Per-shard body (inside shard_map).

    stage_params: this stage's params with a leading length-1 stage dim.
    x: this data-shard's batch [B_local, ...]; only stage 0 consumes it,
    but every stage holds it (replicated over the pipeline axis).
    gather_dims: optional pytree congruent with stage_params of
    (dim, mesh_axis) or None per leaf — fsdp-at-rest composition: the leaf
    arrives sharded on `dim` over `mesh_axis` and is all-gathered here
    before the stage scan (its AD transpose is a reduce-scatter, so grads
    land sharded again — ZeRO-style param/optimizer sharding with one
    gather per stage per step).
    Returns y [B_local, ...] replicated over the pipeline axis.
    """
    n_stages = jax.lax.psum(1, axis)
    stage = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    if gather_dims is not None:
        params = _gather_params(params, gather_dims)

    B = x.shape[0]
    if B < 1:
        raise ValueError("pipeline stage received an empty batch")
    # Largest feasible microbatch count <= requested: the LOCAL batch (after
    # data-axis sharding) must split evenly, and callers size n_microbatches
    # against the global batch.
    M = max(min(n_microbatches, B), 1)
    while B % M:
        M -= 1
    if M != n_microbatches:
        import warnings

        warnings.warn(
            f"pipeline: n_microbatches={n_microbatches} infeasible for local "
            f"batch {B}; using {M} (at M=1 the schedule degrades to "
            f"sequential stages — resize the batch for real pipelining)"
        )
    micro = x.reshape(M, B // M, *x.shape[1:])

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    zero_mb = jnp.zeros_like(micro[0])
    outs0 = jnp.zeros_like(micro)

    def step(carry, t):
        recv, outs = carry
        mb_idx = jnp.clip(t, 0, M - 1)
        feed = jax.lax.dynamic_index_in_dim(micro, mb_idx, keepdims=False)
        # Stage 0 injects fresh microbatches while they last; every other
        # stage consumes what its predecessor sent last tick.
        inject = jnp.logical_and(stage == 0, t < M)
        inp = jnp.where(inject, feed, recv)
        out = fn(params, inp)
        # Last stage banks finished microbatches (valid for t >= P-1).
        k = t - (n_stages - 1)
        bank = jnp.logical_and(stage == n_stages - 1, k >= 0)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(bank, out, jax.lax.dynamic_index_in_dim(outs, jnp.clip(k, 0, M - 1), keepdims=False)),
            jnp.clip(k, 0, M - 1),
            0,
        )
        recv_next = jax.lax.ppermute(out, axis, perm)
        return (recv_next, outs), None

    (recv, outs), _ = jax.lax.scan(
        step, (zero_mb, outs0), jnp.arange(M + n_stages - 1)
    )
    # Results live on the last stage; broadcast so every stage returns the
    # same value (out_specs replicate over the pipeline axis).
    outs = jax.lax.psum(
        jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)), axis
    )
    return outs.reshape(B, *x.shape[1:])


def pipeline_apply(
    fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    *,
    n_microbatches: Optional[int] = None,
    axis: str = "pipeline",
    batch_axes: Sequence[str] = ("data", "fsdp"),
    fsdp_dims=None,
    fsdp_axis: str = "fsdp",
):
    """Apply `fn` (one stage's computation: fn(params, x) -> y, same shape)
    as a pipeline of P stages.

    stacked_params: pytree with a leading stage dim of size P (the pipeline
    mesh-axis size), e.g. stacked layer weights [P, ...].
    x: global batch [B, ...]; B shards over batch_axes; the microbatch
    schedule runs inside each data shard.
    n_microbatches: None derives M = min(4 * P, local batch) — 4P keeps the
    GPipe bubble (P-1)/(M+P-1) near 20% without shrinking microbatches
    into MXU-starving slivers.
    fsdp_dims: optional pytree congruent with stacked_params of per-leaf
    dim index (into the STACKED leaf, so >= 1) to shard over `fsdp_axis`
    at rest — pp x fsdp composition: params live sharded, are all-gathered
    per stage per step, and their grads reduce-scatter back (ZeRO-style).
    Leaves with None (or dims that don't divide) stay replicated.
    """
    from jax import shard_map

    n_stages = mesh.shape[axis]
    batch_axes = tuple(a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    if n_microbatches is None:
        import math

        local_b = x.shape[0] // max(
            math.prod(mesh.shape[a] for a in batch_axes), 1
        )
        # Largest DIVISOR of the local batch <= 4P: the derived default
        # must be exactly feasible (the body's truncation warning is for
        # explicit user values, not for our own derivation).
        n_microbatches = max(
            (m for m in range(1, min(4 * n_stages, local_b) + 1)
             if local_b % m == 0),
            default=1,
        )

    fsdp_size = mesh.shape[fsdp_axis] if fsdp_axis in mesh.axis_names else 1

    def leaf_plan(p, d):
        """(in_spec, gather_dim) for one stacked leaf."""
        if d is None or fsdp_size <= 1 or p.shape[d] % fsdp_size != 0:
            return P(axis), None
        entries = [axis] + [None] * (d - 1) + [fsdp_axis]
        # gather dim is d-1 inside the body (stage dim dropped there)
        return P(*entries), (d - 1, fsdp_axis)

    if fsdp_dims is not None:
        flat_p, treedef = jax.tree_util.tree_flatten(stacked_params)
        flat_d = treedef.flatten_up_to(fsdp_dims)
        plans = [leaf_plan(p, d) for p, d in zip(flat_p, flat_d)]
        param_spec = jax.tree_util.tree_unflatten(treedef, [s for s, _ in plans])
        gather_dims = jax.tree_util.tree_unflatten(treedef, [g for _, g in plans])
    else:
        param_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
        gather_dims = None
    xspec = P(batch_axes if batch_axes else None)
    body = functools.partial(
        _pipeline_body,
        fn=fn,
        n_microbatches=n_microbatches,
        axis=axis,
        gather_dims=gather_dims,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(param_spec, xspec),
        out_specs=xspec,
        check_vma=False,
    )(stacked_params, x)
