"""Pipeline parallelism: GPipe-style microbatch pipelining over the
`pipeline` mesh axis.

Absent from the reference entirely (SURVEY §2.4: PP not built in) — built
TPU-first: each pipeline-axis device holds ONE stage's parameters;
microbatches stream through the stages with `ppermute` hops over ICI, and
the whole schedule is a single `lax.scan` inside `shard_map`, so XLA
overlaps each stage's matmuls with its neighbor transfers and reverse-mode
AD differentiates straight through the schedule (backward pipeline for
free — ppermute's transpose is the reverse ring).

Composes with the other axes: the batch dim shards over ("data", "fsdp")
as usual; stages over "pipeline".
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_body(
    stage_params,
    x: jax.Array,
    *,
    fn: Callable,
    n_microbatches: int,
    axis: str,
):
    """Per-shard body (inside shard_map).

    stage_params: this stage's params with a leading length-1 stage dim.
    x: this data-shard's batch [B_local, ...]; only stage 0 consumes it,
    but every stage holds it (replicated over the pipeline axis).
    Returns y [B_local, ...] replicated over the pipeline axis.
    """
    n_stages = jax.lax.psum(1, axis)
    stage = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    B = x.shape[0]
    if B < 1:
        raise ValueError("pipeline stage received an empty batch")
    # Largest feasible microbatch count <= requested: the LOCAL batch (after
    # data-axis sharding) must split evenly, and callers size n_microbatches
    # against the global batch.
    M = max(min(n_microbatches, B), 1)
    while B % M:
        M -= 1
    if M != n_microbatches:
        import warnings

        warnings.warn(
            f"pipeline: n_microbatches={n_microbatches} infeasible for local "
            f"batch {B}; using {M} (at M=1 the schedule degrades to "
            f"sequential stages — resize the batch for real pipelining)"
        )
    micro = x.reshape(M, B // M, *x.shape[1:])

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    zero_mb = jnp.zeros_like(micro[0])
    outs0 = jnp.zeros_like(micro)

    def step(carry, t):
        recv, outs = carry
        mb_idx = jnp.clip(t, 0, M - 1)
        feed = jax.lax.dynamic_index_in_dim(micro, mb_idx, keepdims=False)
        # Stage 0 injects fresh microbatches while they last; every other
        # stage consumes what its predecessor sent last tick.
        inject = jnp.logical_and(stage == 0, t < M)
        inp = jnp.where(inject, feed, recv)
        out = fn(params, inp)
        # Last stage banks finished microbatches (valid for t >= P-1).
        k = t - (n_stages - 1)
        bank = jnp.logical_and(stage == n_stages - 1, k >= 0)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(bank, out, jax.lax.dynamic_index_in_dim(outs, jnp.clip(k, 0, M - 1), keepdims=False)),
            jnp.clip(k, 0, M - 1),
            0,
        )
        recv_next = jax.lax.ppermute(out, axis, perm)
        return (recv_next, outs), None

    (recv, outs), _ = jax.lax.scan(
        step, (zero_mb, outs0), jnp.arange(M + n_stages - 1)
    )
    # Results live on the last stage; broadcast so every stage returns the
    # same value (out_specs replicate over the pipeline axis).
    outs = jax.lax.psum(
        jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)), axis
    )
    return outs.reshape(B, *x.shape[1:])


def pipeline_apply(
    fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    *,
    n_microbatches: int,
    axis: str = "pipeline",
    batch_axes: Sequence[str] = ("data", "fsdp"),
):
    """Apply `fn` (one stage's computation: fn(params, x) -> y, same shape)
    as a pipeline of P stages.

    stacked_params: pytree with a leading stage dim of size P (the pipeline
    mesh-axis size), e.g. stacked layer weights [P, ...].
    x: global batch [B, ...]; B shards over batch_axes; the microbatch
    schedule runs inside each data shard.
    """
    from jax import shard_map

    batch_axes = tuple(a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    xspec = P(batch_axes if batch_axes else None)
    body = functools.partial(
        _pipeline_body, fn=fn, n_microbatches=n_microbatches, axis=axis
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(param_spec, xspec),
        out_specs=xspec,
        check_vma=False,
    )(stacked_params, x)
