"""Pipeline parallelism: GPipe-style microbatch pipelining over the
`pipeline` mesh axis.

Absent from the reference entirely (SURVEY §2.4: PP not built in) — built
TPU-first: each pipeline-axis device holds ONE stage's parameters;
microbatches stream through the stages with `ppermute` hops over ICI, and
the whole schedule is a single `lax.scan` inside `shard_map`, so XLA
overlaps each stage's matmuls with its neighbor transfers and reverse-mode
AD differentiates straight through the schedule (backward pipeline for
free — ppermute's transpose is the reverse ring).

Composes with the other axes: the batch dim shards over ("data", "fsdp")
as usual; stages over "pipeline".
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _feasible_microbatches(B: int, requested: int) -> int:
    """Largest feasible microbatch count <= requested (the local batch
    must split evenly); warns when an EXPLICIT user value is truncated."""
    M = max(min(requested, B), 1)
    while B % M:
        M -= 1
    if M != requested:
        import warnings

        warnings.warn(
            f"pipeline: n_microbatches={requested} infeasible for local "
            f"batch {B}; using {M} (at M=1 the schedule degrades to "
            f"sequential stages — resize the batch for real pipelining)"
        )
    return M


def _derive_microbatches(mesh, x, batch_axes, n_stages: int) -> int:
    """Default M: the largest DIVISOR of the local batch <= 4P — 4P keeps
    the GPipe bubble (P-1)/(M+P-1) near 20% without shrinking microbatches
    into MXU-starving slivers, and a divisor is exactly feasible (no
    truncation warning for our own derivation)."""
    import math

    local_b = x.shape[0] // max(
        math.prod(mesh.shape[a] for a in batch_axes), 1
    )
    return max(
        (m for m in range(1, min(4 * n_stages, local_b) + 1)
         if local_b % m == 0),
        default=1,
    )


def _gather_params(params, gather_dims):
    """all_gather the fsdp-sharded leaves (see _pipeline_body docstring).
    gather_dims leaves are (dim_index, mesh_axis) tuples or None."""
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(gather_dims)
    gathered = [
        p if gd is None else jax.lax.all_gather(p, gd[1], axis=gd[0], tiled=True)
        for p, gd in zip(flat_p, flat_g)
    ]
    return jax.tree_util.tree_unflatten(treedef, gathered)


def _pipeline_body(
    stage_params,
    x: jax.Array,
    *,
    fn: Callable,
    n_microbatches: int,
    axis: str,
    gather_dims=None,
):
    """Per-shard body (inside shard_map).

    stage_params: this stage's params with a leading length-1 stage dim.
    x: this data-shard's batch [B_local, ...]; only stage 0 consumes it,
    but every stage holds it (replicated over the pipeline axis).
    gather_dims: optional pytree congruent with stage_params of
    (dim, mesh_axis) or None per leaf — fsdp-at-rest composition: the leaf
    arrives sharded on `dim` over `mesh_axis` and is all-gathered here
    before the stage scan (its AD transpose is a reduce-scatter, so grads
    land sharded again — ZeRO-style param/optimizer sharding with one
    gather per stage per step).
    Returns y [B_local, ...] replicated over the pipeline axis.
    """
    n_stages = jax.lax.psum(1, axis)
    stage = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    if gather_dims is not None:
        params = _gather_params(params, gather_dims)

    B = x.shape[0]
    if B < 1:
        raise ValueError("pipeline stage received an empty batch")
    M = _feasible_microbatches(B, n_microbatches)
    micro = x.reshape(M, B // M, *x.shape[1:])

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    zero_mb = jnp.zeros_like(micro[0])
    outs0 = jnp.zeros_like(micro)

    def step(carry, t):
        recv, outs = carry
        mb_idx = jnp.clip(t, 0, M - 1)
        feed = jax.lax.dynamic_index_in_dim(micro, mb_idx, keepdims=False)
        # Stage 0 injects fresh microbatches while they last; every other
        # stage consumes what its predecessor sent last tick.
        inject = jnp.logical_and(stage == 0, t < M)
        inp = jnp.where(inject, feed, recv)
        out = fn(params, inp)
        # Last stage banks finished microbatches (valid for t >= P-1).
        k = t - (n_stages - 1)
        bank = jnp.logical_and(stage == n_stages - 1, k >= 0)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(bank, out, jax.lax.dynamic_index_in_dim(outs, jnp.clip(k, 0, M - 1), keepdims=False)),
            jnp.clip(k, 0, M - 1),
            0,
        )
        recv_next = jax.lax.ppermute(out, axis, perm)
        return (recv_next, outs), None

    (recv, outs), _ = jax.lax.scan(
        step, (zero_mb, outs0), jnp.arange(M + n_stages - 1)
    )
    # Results live on the last stage; broadcast so every stage returns the
    # same value (out_specs replicate over the pipeline axis).
    outs = jax.lax.psum(
        jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)), axis
    )
    return outs.reshape(B, *x.shape[1:])


def pipeline_apply(
    fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    *,
    n_microbatches: Optional[int] = None,
    axis: str = "pipeline",
    batch_axes: Sequence[str] = ("data", "fsdp"),
    fsdp_dims=None,
    fsdp_axis: str = "fsdp",
):
    """Apply `fn` (one stage's computation: fn(params, x) -> y, same shape)
    as a pipeline of P stages.

    stacked_params: pytree with a leading stage dim of size P (the pipeline
    mesh-axis size), e.g. stacked layer weights [P, ...].
    x: global batch [B, ...]; B shards over batch_axes; the microbatch
    schedule runs inside each data shard.
    n_microbatches: None derives M = min(4 * P, local batch) — 4P keeps the
    GPipe bubble (P-1)/(M+P-1) near 20% without shrinking microbatches
    into MXU-starving slivers.
    fsdp_dims: optional pytree congruent with stacked_params of per-leaf
    dim index (into the STACKED leaf, so >= 1) to shard over `fsdp_axis`
    at rest — pp x fsdp composition: params live sharded, are all-gathered
    per stage per step, and their grads reduce-scatter back (ZeRO-style).
    Leaves with None (or dims that don't divide) stay replicated.
    """
    from ray_tpu.parallel.sharding import shard_map

    n_stages = mesh.shape[axis]
    batch_axes = tuple(a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    if n_microbatches is None:
        n_microbatches = _derive_microbatches(mesh, x, batch_axes, n_stages)

    fsdp_size = mesh.shape[fsdp_axis] if fsdp_axis in mesh.axis_names else 1

    def leaf_plan(p, d):
        """(in_spec, gather_dim) for one stacked leaf."""
        if d is None or fsdp_size <= 1 or p.shape[d] % fsdp_size != 0:
            return P(axis), None
        entries = [axis] + [None] * (d - 1) + [fsdp_axis]
        # gather dim is d-1 inside the body (stage dim dropped there)
        return P(*entries), (d - 1, fsdp_axis)

    if fsdp_dims is not None:
        flat_p, treedef = jax.tree_util.tree_flatten(stacked_params)
        flat_d = treedef.flatten_up_to(fsdp_dims)
        plans = [leaf_plan(p, d) for p, d in zip(flat_p, flat_d)]
        param_spec = jax.tree_util.tree_unflatten(treedef, [s for s, _ in plans])
        gather_dims = jax.tree_util.tree_unflatten(treedef, [g for _, g in plans])
    else:
        param_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
        gather_dims = None
    xspec = P(batch_axes if batch_axes else None)
    body = functools.partial(
        _pipeline_body,
        fn=fn,
        n_microbatches=n_microbatches,
        axis=axis,
        gather_dims=gather_dims,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(param_spec, xspec),
        out_specs=xspec,
        check_vma=False,
    )(stacked_params, x)


# ---------------------------------------------------------------------------
# 1F1B schedule (no reference counterpart — SURVEY §2.4 names pp as
# TPU-native work; the schedule itself is the PipeDream-flush / Megatron
# non-interleaved 1F1B).


def _1f1b_body(
    stage_params,
    x: jax.Array,
    target,
    *,
    fn: Callable,
    loss_fn: Callable,
    n_microbatches: int,
    axis: str,
):
    """Per-shard fused forward+backward 1F1B schedule.

    GPipe differentiates the forward scan with autodiff, so every one of
    the M in-flight microbatch activations (plus scan residuals across
    M+P-1 ticks) is live at the backward's start — activation memory grows
    linearly with M.  1F1B starts each microbatch's backward as soon as
    the last stage finishes its forward, so a stage holds at most
    2(P-1-s)+1 <= 2P-1 in-flight inputs: the residual ring here is sized
    by the PIPELINE DEPTH, not the microbatch count.  The backward is
    explicit (jax.vjp per slot, recomputing the stage forward — remat of
    one stage per microbatch), cotangents ride the reverse ring, and
    parameter gradients accumulate locally, so the whole fwd+bwd schedule
    is ONE lockstep lax.scan of M + 2P - 2 ticks.

    Tick roles (lockstep SPMD — every device executes both slots, masked
    when idle): F slot at stage s handles microbatch m = t - s; B slot
    handles m = t - (2P - 2 - s); the last stage's B follows its F in the
    SAME tick (loss cotangent computed in place).

    Returns (loss_sum/M, stage_grads) with grads carrying the stage dim.
    """
    n_stages = jax.lax.psum(1, axis)
    stage = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    B = x.shape[0]
    M = _feasible_microbatches(B, n_microbatches)
    micro = x.reshape(M, B // M, *x.shape[1:])
    tgt_micro = target.reshape(M, B // M, *target.shape[1:])

    R = 2 * n_stages - 1  # residual ring: max in-flight per stage
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    perm_bwd = [(i, (i - 1) % n_stages) for i in range(n_stages)]

    zero_mb = jnp.zeros_like(micro[0])
    ring0 = jnp.zeros((R,) + micro.shape[1:], micro.dtype)
    grad0 = jax.tree_util.tree_map(jnp.zeros_like, params)

    def step(carry, t):
        recv_f, recv_b, ring, gacc, loss_acc = carry

        # ---- F slot: stage s runs microbatch m_f = t - s ----
        m_f = t - stage
        f_active = jnp.logical_and(m_f >= 0, m_f < M)
        feed = jax.lax.dynamic_index_in_dim(
            micro, jnp.clip(m_f, 0, M - 1), keepdims=False
        )
        x_in = jnp.where(stage == 0, feed, recv_f)
        y_out = fn(params, x_in)
        # Bank this slot's input for the backward (ring-indexed by m).
        slot = jnp.clip(m_f, 0, M - 1) % R
        ring = jax.lax.dynamic_update_index_in_dim(
            ring,
            jnp.where(
                f_active,
                x_in,
                jax.lax.dynamic_index_in_dim(ring, slot, keepdims=False),
            ),
            slot,
            0,
        )

        # ---- B slot: stage s runs microbatch m_b = t - (2P-2-s) ----
        m_b = t - (2 * n_stages - 2 - stage)
        b_active = jnp.logical_and(m_b >= 0, m_b < M)
        bslot = jnp.clip(m_b, 0, M - 1) % R
        x_saved = jax.lax.dynamic_index_in_dim(ring, bslot, keepdims=False)
        tgt = jax.lax.dynamic_index_in_dim(
            tgt_micro, jnp.clip(m_b, 0, M - 1), keepdims=False
        )

        # Backward via remat'd vjp of this stage's forward — ONE stage
        # backward per tick: the cotangent is SELECTED first (last stage
        # seeds it from the loss of the microbatch it just finished — its
        # m_f == m_b this tick; other stages use the ring delivery).
        is_last = stage == n_stages - 1
        y_pred, pull_stage = jax.vjp(fn, params, x_saved)
        loss_here, pull_loss = jax.vjp(lambda yy: loss_fn(yy, tgt), y_pred)
        (dy_loss,) = pull_loss(jnp.ones_like(loss_here))
        dy = jnp.where(is_last, dy_loss, recv_b)
        dp, dx = pull_stage(dy)
        gacc = jax.tree_util.tree_map(
            lambda g, d: g + jnp.where(b_active, d, jnp.zeros_like(d)),
            gacc, dp,
        )
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(b_active, is_last), loss_here, 0.0
        )

        recv_f_next = jax.lax.ppermute(y_out, axis, perm_fwd)
        recv_b_next = jax.lax.ppermute(
            jnp.where(b_active, dx, jnp.zeros_like(dx)), axis, perm_bwd
        )
        return (recv_f_next, recv_b_next, ring, gacc, loss_acc), None

    T = M + 2 * n_stages - 2
    (_, _, _, gacc, loss_acc), _ = jax.lax.scan(
        step,
        (zero_mb, zero_mb, ring0, grad0, jnp.zeros(())),
        jnp.arange(T),
    )
    # Loss lives on the last stage; grads live per stage.  Broadcast the
    # loss; re-attach the stage dim to the grads.
    loss = jax.lax.psum(
        jnp.where(stage == n_stages - 1, loss_acc, 0.0), axis
    ) / M
    grads = jax.tree_util.tree_map(lambda g: (g / M)[None], gacc)
    return loss, grads


def pipeline_train_step_1f1b(
    fn: Callable,
    loss_fn: Callable,
    stacked_params,
    x: jax.Array,
    target,
    mesh: Mesh,
    *,
    n_microbatches: Optional[int] = None,
    axis: str = "pipeline",
    batch_axes: Sequence[str] = ("data", "fsdp"),
):
    """Fused 1F1B training step: returns (mean_loss, stacked_grads).

    Selectable alternative to differentiating pipeline_apply (GPipe): same
    numbers, bounded activation memory (see _1f1b_body).  `loss_fn(y,
    target) -> scalar` is the PER-MICROBATCH mean loss evaluated by the
    last stage; gradients come back with the leading stage dim, mean-
    normalized over microbatches, and psum'd over the batch axes (data-
    parallel reduction included, like any SPMD train step)."""
    from ray_tpu.parallel.sharding import shard_map

    n_stages = mesh.shape[axis]
    batch_axes = tuple(
        a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1
    )
    if n_microbatches is None:
        n_microbatches = _derive_microbatches(mesh, x, batch_axes, n_stages)

    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    xspec = P(batch_axes if batch_axes else None)

    def body(p, xx, tt):
        loss, grads = _1f1b_body(
            p, xx, tt, fn=fn, loss_fn=loss_fn,
            n_microbatches=n_microbatches, axis=axis,
        )
        # Data-parallel reduction over the batch axes.
        for a in batch_axes:
            loss = jax.lax.pmean(loss, a)
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, a), grads
            )
        return loss, grads

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(param_spec, xspec, xspec),
        out_specs=(P(), param_spec),
        check_vma=False,
    )(stacked_params, x, target)
