"""Device-mesh management: the TPU-native replacement for NCCL process groups.

In the reference, distributed tensor communication is a *runtime library*
(ray.util.collective NCCLGroup, python/ray/util/collective/collective_group/
nccl_collective_group.py:127, and torch.distributed in
python/ray/train/torch/config.py:113).  On TPU, collectives are *compiled into
the XLA program* and ride ICI; what remains at runtime is (a) describing the
mesh, (b) bootstrapping every host process into the same multi-host XLA
computation, and (c) mapping logical parallelism axes (data/fsdp/tensor/seq/
expert) onto physical mesh axes.  This module owns (a) and (c); bootstrap.py
owns (b).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical logical axis order.  Physical layout: the innermost axes ("tensor",
# "seq") change fastest so they land on the tightest ICI loops when the mesh is
# built from a pod topology; "data" is outermost so data-parallel replicas may
# span DCN between slices.
AXIS_ORDER: Tuple[str, ...] = ("data", "fsdp", "expert", "pipeline", "seq", "tensor")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape over named parallelism axes.

    Sizes of -1 mean "absorb remaining devices" (at most one axis may be -1).
    Axes of size 1 are still materialized so sharding rules can always refer to
    every canonical axis name.
    """

    data: int = -1
    fsdp: int = 1
    expert: int = 1
    pipeline: int = 1
    seq: int = 1
    tensor: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return MeshSpec(**sizes)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return AXIS_ORDER

    def shape(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    def size(self) -> int:
        return math.prod(s for s in self.shape() if s > 0)


def remesh_spec(spec: MeshSpec, n_devices: int) -> MeshSpec:
    """Re-resolve a mesh spec after an elastic re-mesh changed the device
    count (host lost → shrink, replacement host returned → grow).

    A spec with a -1 wildcard re-absorbs the new count directly.  A fully
    fixed spec re-shapes along its "data" axis (the DCN-spanning axis —
    replicas are what a host-count change adds or removes; ICI-bound axes
    like tensor/fsdp would change the compiled program's communication
    pattern) and fails with an actionable error when that isn't possible.
    """
    sizes = {a: getattr(spec, a) for a in AXIS_ORDER}
    if any(s == -1 for s in sizes.values()):
        return spec.resolve(n_devices)
    other = math.prod(s for a, s in sizes.items() if a != "data")
    if other <= 0 or n_devices % other != 0:
        raise ValueError(
            f"cannot re-mesh {sizes} onto {n_devices} devices: the non-data "
            f"axes need a multiple of {other}; use data=-1 for elastic "
            "training or resize the gang to a compatible host count"
        )
    sizes["data"] = n_devices // other
    return MeshSpec(**sizes)


def build_mesh(
    spec: Optional[MeshSpec] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Materialize a jax.sharding.Mesh from a MeshSpec.

    Uses mesh_utils.create_device_mesh so the physical device order respects
    ICI topology (nearest-neighbor rings per axis) on real TPU slices; on CPU
    (virtual device testing) it falls back to a simple reshape.
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).resolve(len(devices))
    shape = spec.shape()
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, spec.axis_names)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    device = device or jax.devices()[0]
    return build_mesh(MeshSpec(data=1), devices=[device])


def mesh_axis_sizes(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
