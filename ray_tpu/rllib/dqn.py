"""DQN: replay-buffer off-policy learning (double DQN + target network).

ray: rllib/algorithms/dqn/ — the second algorithm on the Algorithm surface,
showing the stack generalizes beyond on-policy PPO.  TPU-first: the whole
sampled-minibatch update (gather, double-DQN targets, huber loss, adam) is
one jitted function; rollout actors run epsilon-greedy over vectorized
envs with a single jitted argmax per step.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_vector_env


class ReplayBuffer:
    """Uniform ring buffer of transitions (ray: utils/replay_buffers)."""

    def __init__(self, capacity: int, obs_size: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_size), np.float32)
        self.next_obs = np.zeros((capacity, obs_size), np.float32)
        self.actions = np.zeros(capacity, np.int64)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.idx = 0
        self.size = 0

    def add_batch(self, obs, actions, rewards, next_obs, dones):
        n = len(actions)
        idxs = (self.idx + np.arange(n)) % self.capacity
        self.obs[idxs] = obs
        self.actions[idxs] = actions
        self.rewards[idxs] = rewards
        self.next_obs[idxs] = next_obs
        self.dones[idxs] = dones
        self.idx = int((self.idx + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))

    def sample(self, batch_size: int, rng: np.random.Generator):
        idxs = rng.integers(0, self.size, size=batch_size)
        return (
            self.obs[idxs],
            self.actions[idxs],
            self.rewards[idxs],
            self.next_obs[idxs],
            self.dones[idxs],
        )


class _DQNRunner:
    """Rollout actor: epsilon-greedy transitions over a vectorized env."""

    def __init__(self, env, num_envs: int, seed: int):
        self.env = make_vector_env(env, num_envs, seed=seed)
        self.rng = np.random.default_rng(seed)
        self._apply = None
        self._params = None
        self._obs = self.env.reset(seed=seed)

    def _q_values(self, obs):
        import jax
        import jax.numpy as jnp

        if self._apply is None:
            from ray_tpu.rllib.policy import apply_policy

            self._apply = jax.jit(lambda p, o: apply_policy(p, o)[0])
        return np.asarray(self._apply(self._params, jnp.asarray(obs)))

    def collect(self, weights, n_steps: int, epsilon: float) -> Dict[str, Any]:
        import jax.numpy as jnp
        import jax

        self._params = jax.tree_util.tree_map(jnp.asarray, weights)
        N = self.env.num_envs
        obs_l, act_l, rew_l, next_l, done_l = [], [], [], [], []
        obs = self._obs
        for _ in range(n_steps):
            q = self._q_values(obs)
            greedy = q.argmax(axis=1)
            explore = self.rng.random(N) < epsilon
            actions = np.where(explore, self.rng.integers(0, q.shape[1], N), greedy)
            final_obs, rewards, terminated, truncated = self.env.step(actions)
            obs_l.append(obs)
            act_l.append(actions)
            rew_l.append(rewards)
            next_l.append(final_obs)
            done_l.append(terminated.astype(np.float32))  # truncation bootstraps
            obs = self.env.current_obs()
        self._obs = obs
        return {
            "obs": np.concatenate(obs_l),
            "actions": np.concatenate(act_l),
            "rewards": np.concatenate(rew_l),
            "next_obs": np.concatenate(next_l),
            "dones": np.concatenate(done_l),
            "episode_returns": self.env.drain_episode_returns(),
            "steps": n_steps * N,
        }

    def ping(self):
        return "pong"


class DQNConfig:
    def __init__(self):
        self.env: Optional[str | Callable] = None
        self.num_env_runners = 1
        self.num_envs_per_runner = 8
        self.rollout_length = 32
        self.gamma = 0.99
        self.lr = 1e-3
        self.buffer_capacity = 50_000
        self.learn_batch_size = 128
        self.updates_per_iteration = 32
        self.target_sync_every = 4  # iterations
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_iters = 30
        self.hidden = (64, 64)
        self.seed = 0
        # Offline training (ray: AlgorithmConfig.offline_data): when set,
        # no env runners spawn and the replay buffer is bulk-loaded from
        # the logged dataset — training never steps an environment.
        self.offline_input = None

    def environment(self, env) -> "DQNConfig":
        self.env = env
        return self

    def offline_data(self, input_) -> "DQNConfig":
        """input_: parquet path(s) from offline.write_experiences, or a
        ray_tpu.data Dataset with the experience columns."""
        self.offline_input = input_
        return self

    def env_runners(self, num_env_runners=1, num_envs_per_runner=8, rollout_length=32):
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_runner
        self.rollout_length = rollout_length
        return self

    def training(self, **kw) -> "DQNConfig":
        valid = {
            "gamma", "lr", "buffer_capacity", "learn_batch_size",
            "updates_per_iteration", "target_sync_every", "epsilon_start",
            "epsilon_end", "epsilon_decay_iters", "hidden",
        }
        for k, v in kw.items():
            if k not in valid:
                raise TypeError(f"unknown DQN training option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, seed: int = 0) -> "DQNConfig":
        self.seed = seed
        return self

    def build(self) -> "DQN":
        if self.env is None and self.offline_input is None:
            raise ValueError("call .environment(env) or .offline_data(...) first")
        return DQN(self)


def _make_learner(cfg: DQNConfig, obs_size: int, num_actions: int):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.policy import apply_policy, init_policy_params

    opt = optax.adam(cfg.lr)

    def init_state(seed: int):
        params = init_policy_params(
            jax.random.PRNGKey(seed), obs_size, num_actions, cfg.hidden
        )
        return {
            "params": params,
            "target": jax.tree_util.tree_map(jnp.copy, params),
            "opt_state": opt.init(params),
        }

    def q_of(params, obs):
        return apply_policy(params, obs)[0]  # logits head doubles as Q head

    def update_many(state, batches):
        """All of an iteration's updates as ONE scanned program (same
        pattern as the PPO learner): batches are stacked [U, B, ...]."""

        def one(carry, batch):
            params, opt_state = carry
            obs, actions, rewards, next_obs, dones = batch

            def loss_fn(p):
                q = q_of(p, obs)
                q_sa = jnp.take_along_axis(q, actions[:, None], axis=1)[:, 0]
                # double DQN: online net argmax, target net evaluation
                next_a = q_of(p, next_obs).argmax(axis=1)
                next_q = jnp.take_along_axis(
                    q_of(state["target"], next_obs), next_a[:, None], axis=1
                )[:, 0]
                target = rewards + cfg.gamma * (1.0 - dones) * next_q
                return optax.huber_loss(q_sa, jax.lax.stop_gradient(target)).mean()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            one, (state["params"], state["opt_state"]), batches
        )
        return {**state, "params": params, "opt_state": opt_state}, losses.mean()

    def sync_target(state):
        import jax

        return {**state, "target": jax.tree_util.tree_map(jnp.copy, state["params"])}

    return init_state, jax.jit(update_many), sync_target


class DQN:
    """ray: Algorithm surface — train()/save/restore/stop."""

    def __init__(self, config: DQNConfig):
        self.config = config
        ray_tpu.init(ignore_reinit_error=True)
        self.offline = None
        if config.offline_input is not None:
            # Offline mode (ray: offline/dataset_reader.py): shapes come
            # from the logged data; training steps NO environment.
            from ray_tpu.rllib.offline import OfflineData

            self.offline = OfflineData(config.offline_input)
            if config.env is not None:
                # The env's declared action space beats inference from the
                # logged actions (a behavior policy that never emitted some
                # action would silently shrink the Q head).
                probe = make_vector_env(config.env, 1, seed=0)
                self._obs_size = probe.observation_size
                self._num_actions = probe.num_actions
                if self.offline.obs_size != self._obs_size:
                    raise ValueError(
                        f"offline data obs dim {self.offline.obs_size} != "
                        f"env obs dim {self._obs_size}"
                    )
                if self.offline.num_actions > self._num_actions:
                    # take_along_axis would silently CLAMP out-of-range
                    # action indices — corrupt Q targets, no error.
                    raise ValueError(
                        f"offline data contains action ids up to "
                        f"{self.offline.num_actions - 1}, but the env "
                        f"declares only {self._num_actions} actions"
                    )
            else:
                self._obs_size = self.offline.obs_size
                self._num_actions = self.offline.num_actions
        else:
            probe = make_vector_env(config.env, 1, seed=0)
            if getattr(probe, "continuous", False):
                raise ValueError(
                    "DQN needs a discrete-action env; use SAC for "
                    "continuous control"
                )
            self._obs_size = probe.observation_size
            self._num_actions = probe.num_actions
        init_state, self._update, self._sync = _make_learner(
            config, self._obs_size, self._num_actions
        )
        self._state = init_state(config.seed)
        capacity = config.buffer_capacity
        if self.offline is not None:
            # The buffer must hold the WHOLE logged dataset — ring-wrapping
            # would silently train on only the last `capacity` rows.
            capacity = max(capacity, self.offline.size)
        self.buffer = ReplayBuffer(capacity, self._obs_size)
        self._rng = np.random.default_rng(config.seed)
        # Serializes the shared RNG (and lazy jit init) between the train
        # loop and PolicyServer inference threads — numpy Generators are
        # not thread-safe.
        import threading as _threading

        self._action_lock = _threading.Lock()
        self._single_apply = None
        self.runners = []
        if self.offline is None:
            Runner = ray_tpu.remote(_DQNRunner)
            self.runners = [
                Runner.remote(
                    config.env,
                    config.num_envs_per_runner,
                    config.seed + 997 * (i + 1),
                )
                for i in range(config.num_env_runners)
            ]
            ray_tpu.get([r.ping.remote() for r in self.runners], timeout=120)
        else:
            self.offline.fill_buffer(self.buffer)
            # Release the reader's materialized copy: the buffer holds the
            # data now; keeping both doubles resident memory for the run.
            self.offline._cols = None
        self._eval_runner = None
        self._eval_env = None
        self.iteration = 0
        self._total_steps = 0
        self._episode_returns: List[float] = []

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self._state["params"])

    def compute_single_action(self, obs, explore: bool = True) -> int:
        """One action for one observation (the PolicyServer inference
        hook; ray: Algorithm.compute_single_action).  explore=True applies
        the current epsilon schedule.

        Thread-safe: the PolicyServer calls this concurrently from its
        connection threads while the training loop samples the replay
        buffer — the shared numpy Generator (not thread-safe) and the lazy
        jit init are serialized under a dedicated lock."""
        import jax
        import jax.numpy as jnp

        with self._action_lock:
            if self._single_apply is None:
                from ray_tpu.rllib.policy import apply_policy

                self._single_apply = jax.jit(
                    lambda p, o: apply_policy(p, o)[0]
                )
            if explore and self._rng.random() < self._epsilon():
                return int(self._rng.integers(0, self._num_actions))
            params = self._state["params"]
        q = self._single_apply(params, jnp.asarray(obs)[None, :])
        return int(np.asarray(q)[0].argmax())

    def _epsilon(self) -> float:
        c = self.config
        frac = min(self.iteration / max(c.epsilon_decay_iters, 1), 1.0)
        return c.epsilon_start + frac * (c.epsilon_end - c.epsilon_start)

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        c = self.config
        t0 = time.time()
        eps = self._epsilon()
        if self.runners:
            w_ref = ray_tpu.put(self.get_weights())
            outs = ray_tpu.get(
                [r.collect.remote(w_ref, c.rollout_length, eps) for r in self.runners],
                timeout=300,
            )
            for o in outs:
                self.buffer.add_batch(
                    o["obs"], o["actions"], o["rewards"], o["next_obs"], o["dones"]
                )
                self._episode_returns.extend(o["episode_returns"])
                self._total_steps += o["steps"]
            self._episode_returns = self._episode_returns[-100:]

        loss = 0.0
        if self.buffer.size >= c.learn_batch_size:
            # One stacked [U, B, ...] transfer + one scanned dispatch for
            # the whole iteration's updates.  (RNG under the action lock:
            # PolicyServer threads share this Generator.)
            with self._action_lock:
                stacked = [
                    self.buffer.sample(c.learn_batch_size, self._rng)
                    for _ in range(c.updates_per_iteration)
                ]
            batches = tuple(
                jnp.asarray(np.stack([s[i] for s in stacked])) for i in range(5)
            )
            self._state, loss = self._update(self._state, batches)
        self.iteration += 1
        if self.iteration % c.target_sync_every == 0:
            self._state = self._sync(self._state)
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (
                float(np.mean(self._episode_returns)) if self._episode_returns else 0.0
            ),
            "epsilon": eps,
            "loss": float(loss),
            "num_env_steps_sampled": self._total_steps,
            "buffer_size": self.buffer.size,
            "time_this_iter_s": time.time() - t0,
        }

    def evaluate(self, *, num_steps: int = 500, env=None) -> Dict[str, Any]:
        """Greedy-policy evaluation on a DEDICATED eval runner actor —
        separate from the training runners, so evaluation never perturbs
        the epsilon-greedy collection stream (ray: evaluation_config /
        evaluation_num_workers split).  Offline-trained algorithms pass
        `env` (or set config.env) to measure the learned policy."""
        env = env or self.config.env
        if env is None:
            raise ValueError("evaluate() needs an env (config.env or env=)")
        if self._eval_runner is None or self._eval_env != env:
            if self._eval_runner is not None:
                try:
                    ray_tpu.kill(self._eval_runner)
                except Exception:
                    pass
            Runner = ray_tpu.remote(_DQNRunner)
            self._eval_runner = Runner.remote(
                env, self.config.num_envs_per_runner, self.config.seed + 31337
            )
            self._eval_env = env
            ray_tpu.get(self._eval_runner.ping.remote(), timeout=120)
        w_ref = ray_tpu.put(self.get_weights())
        out = ray_tpu.get(
            self._eval_runner.collect.remote(w_ref, num_steps, 0.0), timeout=300
        )
        returns = out["episode_returns"]
        return {
            "evaluation": {
                "episode_reward_mean": (
                    float(np.mean(returns)) if returns else 0.0
                ),
                "episodes": len(returns),
                "num_env_steps": out["steps"],
            }
        }

    def save(self, path: Optional[str] = None) -> str:
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        host = jax.tree_util.tree_map(np.asarray, self._state)
        return Checkpoint.from_dict(
            {"learner_state": host, "iteration": self.iteration}
        ).to_directory(path)

    def restore(self, path: str) -> None:
        import jax
        import jax.numpy as jnp

        from ray_tpu.air.checkpoint import Checkpoint

        d = Checkpoint.from_directory(path).to_dict()
        self._state = jax.tree_util.tree_map(jnp.asarray, d["learner_state"])
        self.iteration = d["iteration"]

    def stop(self) -> None:
        for r in self.runners + (
            [self._eval_runner] if self._eval_runner is not None else []
        ):
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners = []
        self._eval_runner = None
