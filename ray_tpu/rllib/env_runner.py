"""EnvRunner: the rollout actor.

ray: rllib/evaluation/rollout_worker.py:165,885 (RolloutWorker.sample) —
TPU-first redesign: the runner steps a VECTORIZED env and calls the policy
once per step on the whole env batch (one jitted dispatch), instead of the
reference's per-env Python sampling loop.  GAE post-processing happens
runner-side (matching the reference's postprocess_trajectory placement) so
the learner receives ready-to-train columns.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.env import make_vector_env
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    LOGPS,
    OBS,
    RETURNS,
    SampleBatch,
    compute_gae,
)


class EnvRunner:
    """Actor payload: owns a VectorEnv + a JaxPolicy copy."""

    def __init__(
        self,
        env: str | Callable,
        num_envs: int,
        rollout_length: int,
        *,
        gamma: float = 0.99,
        lam: float = 0.95,
        seed: int = 0,
        hidden=(64, 64),
        module=None,
    ):
        self.env = make_vector_env(env, num_envs, seed=seed)
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.lam = lam
        self.policy = JaxPolicy(
            self.env.observation_size, self.env.num_actions, seed=seed,
            hidden=hidden, module=module,
        )
        self._obs = self.env.reset(seed=seed)

    def set_weights(self, weights) -> None:
        self.policy.set_weights(weights)

    def sample(self, weights: Optional[Any] = None) -> Dict[str, Any]:
        """Collect rollout_length × num_envs steps; returns a flat
        SampleBatch (dict of [T*N] arrays) + episode stats."""
        if weights is not None:
            self.policy.set_weights(weights)
        T, N = self.rollout_length, self.env.num_envs
        obs_buf = np.zeros((T, N, self.env.observation_size), dtype=np.float32)
        act_buf = np.zeros((T, N), dtype=np.int64)
        logp_buf = np.zeros((T, N), dtype=np.float32)
        val_buf = np.zeros((T, N), dtype=np.float32)
        rew_buf = np.zeros((T, N), dtype=np.float32)
        done_buf = np.zeros((T, N), dtype=bool)

        obs = self._obs
        for t in range(T):
            actions, logps, values = self.policy.compute_actions(obs)
            obs_buf[t] = obs
            act_buf[t] = actions
            logp_buf[t] = logps
            val_buf[t] = values
            final_obs, rewards, terminated, truncated = self.env.step(actions)
            if truncated.any():
                # Time-limit cutoffs are NOT terminations: bootstrap the
                # truncated state's value into the reward so GAE doesn't
                # learn conflicting V-targets for late-episode states.
                # Evaluate on the full fixed-shape [N] batch and index on
                # host — a final_obs[idx] batch would retrigger XLA
                # compilation for every distinct truncation count.
                idx = np.nonzero(truncated)[0]
                _, _, v_final = self.policy.compute_actions(final_obs)
                rewards = rewards.copy()
                rewards[idx] += self.gamma * v_final[idx]
            rew_buf[t] = rewards
            done_buf[t] = terminated | truncated  # both cut the GAE trace
            obs = self.env.current_obs()
        self._obs = obs

        # Bootstrap the value of the final observation for unfinished envs.
        _, _, last_values = self.policy.compute_actions(obs)
        adv, rets = compute_gae(
            rew_buf, val_buf, done_buf, last_values, self.gamma, self.lam
        )
        # Only the columns the learner consumes are shipped (REWARDS/DONES/
        # VALUES already did their job in the GAE computation above).
        batch = SampleBatch(
            {
                OBS: obs_buf.reshape(T * N, -1),
                ACTIONS: act_buf.reshape(-1),
                LOGPS: logp_buf.reshape(-1),
                ADVANTAGES: adv.reshape(-1),
                RETURNS: rets.reshape(-1),
            }
        )
        return {
            "batch": dict(batch),
            "episode_returns": self.env.drain_episode_returns(),
            "steps": T * N,
        }

    def sample_trajectory(
        self, weights: Optional[Any] = None, weights_version: int = 0
    ) -> Dict[str, Any]:
        """Collect a TIME-MAJOR raw trajectory for off-policy learners
        (IMPALA — ray: rllib/algorithms/impala/impala.py:478).

        Unlike `sample()` (which post-processes GAE runner-side for PPO),
        this ships the behavior policy's raw experience: the learner computes
        values under its OWN current params and applies V-trace importance
        correction for the sampling lag.  `next_obs` is the pre-reset
        observation of every step, so the learner can bootstrap through
        time-limit truncations exactly (terminated cuts the return;
        truncated bootstraps V(next_obs) but still cuts the trace).
        """
        if weights is not None:
            self.policy.set_weights(weights)
        T, N = self.rollout_length, self.env.num_envs
        obs_buf = np.zeros((T, N, self.env.observation_size), dtype=np.float32)
        next_obs_buf = np.zeros_like(obs_buf)
        act_buf = np.zeros((T, N), dtype=np.int64)
        logp_buf = np.zeros((T, N), dtype=np.float32)
        rew_buf = np.zeros((T, N), dtype=np.float32)
        term_buf = np.zeros((T, N), dtype=bool)
        done_buf = np.zeros((T, N), dtype=bool)

        obs = self._obs
        for t in range(T):
            actions, logps, _ = self.policy.compute_actions(obs)
            obs_buf[t] = obs
            act_buf[t] = actions
            logp_buf[t] = logps
            final_obs, rewards, terminated, truncated = self.env.step(actions)
            next_obs_buf[t] = final_obs
            rew_buf[t] = rewards
            term_buf[t] = terminated
            done_buf[t] = terminated | truncated
            obs = self.env.current_obs()
        self._obs = obs

        return {
            "batch": {
                OBS: obs_buf,
                "next_obs": next_obs_buf,
                ACTIONS: act_buf,
                LOGPS: logp_buf,
                "rewards": rew_buf,
                "terminateds": term_buf,
                "dones": done_buf,
            },
            "episode_returns": self.env.drain_episode_returns(),
            "steps": T * N,
            "weights_version": weights_version,
        }

    def ping(self) -> str:
        return "pong"
