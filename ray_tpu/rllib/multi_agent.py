"""Multi-agent RL: env API, rollout runner, and multi-policy PPO.

ray: rllib/env/multi_agent_env.py (MultiAgentEnv — dict-keyed obs/action/
reward spaces per agent) + the policy-mapping machinery in
rllib/policy/policy_map.py.  TPU-first redesign: every agent's env axis is
VECTORIZED (an agent's observations across N env copies are one [N, obs]
batch → one jitted policy call per agent per step), and each policy's
PPO update remains the single fused lax.scan program from ppo.py — the
multi-agent layer is pure orchestration around the same learner.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import CartPoleVectorEnv
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    LOGPS,
    OBS,
    RETURNS,
    SampleBatch,
    compute_gae,
)


class MultiAgentVectorEnv:
    """N vectorized copies of an M-agent environment.

    Dict-keyed batched surface (ray: MultiAgentEnv's per-agent dicts,
    vectorized here): reset/step take and return {agent_id: [N, ...]}.
    Agents are fixed for the episode (no agent death/spawn in v1).
    """

    num_envs: int
    agent_ids: List[str]

    def observation_size(self, agent_id: str) -> int:
        raise NotImplementedError

    def num_actions(self, agent_id: str) -> int:
        raise NotImplementedError

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, np.ndarray]):
        """actions {agent: [N]} → (final_obs {agent: [N, obs]},
        rewards {agent: [N]}, terminated [N], truncated [N]).
        Termination is per-ENV (all agents end together — the common
        cooperative/competitive episode structure)."""
        raise NotImplementedError

    def current_obs(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def drain_episode_returns(self) -> Dict[str, list]:
        raise NotImplementedError


class MultiAgentCartPole(MultiAgentVectorEnv):
    """M independent CartPoles sharing an episode clock (ray: the
    MultiAgentCartPole used across rllib's multi-agent test suites).  An
    env copy ends when EVERY agent's pole has dropped (failed agents
    accrue zero reward while waiting) or the step cap hits."""

    def __init__(self, num_envs: int = 8, num_agents: int = 2, seed: int = 0):
        self.num_envs = num_envs
        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {
            aid: CartPoleVectorEnv(num_envs, seed=seed + 91 * i)
            for i, aid in enumerate(self.agent_ids)
        }
        self._alive = {
            aid: np.ones(num_envs, dtype=bool) for aid in self.agent_ids
        }
        self._steps = np.zeros(num_envs, dtype=np.int64)
        self._ep_return = {
            aid: np.zeros(num_envs) for aid in self.agent_ids
        }
        self.completed: Dict[str, list] = {aid: [] for aid in self.agent_ids}
        self.max_steps = 200

    def observation_size(self, agent_id):
        return 4

    def num_actions(self, agent_id):
        return 2

    def reset(self, seed=None):
        out = {}
        for i, (aid, env) in enumerate(self._envs.items()):
            # Distinct per-agent seed offsets: one shared seed would give
            # every agent an identical RNG stream (perfectly correlated
            # trajectories — degenerate experience for pooled policies).
            out[aid] = env.reset(None if seed is None else seed + 91 * i)
            self._alive[aid][:] = True
            self._ep_return[aid][:] = 0.0
        self._steps[:] = 0
        return out

    def step(self, actions):
        N = self.num_envs
        final_obs, rewards = {}, {}
        for aid, env in self._envs.items():
            obs_a, rew_a, term_a, trunc_a = env.step(actions[aid])
            # The wrapper tracks episode returns itself: discard the
            # sub-env's own completed-episode list or it grows unbounded
            # across the run (one float per sub-episode per agent forever).
            env.completed_episode_returns.clear()
            # A dropped pole freezes that agent's reward; its sub-env auto-
            # reset but the shared episode keeps running for the others.
            rew_a = rew_a * self._alive[aid]
            self._alive[aid] &= ~(term_a | trunc_a)
            self._ep_return[aid] += rew_a
            final_obs[aid] = obs_a
            rewards[aid] = rew_a
        self._steps += 1
        all_done = ~np.logical_or.reduce(
            [self._alive[aid] for aid in self.agent_ids]
        )
        terminated = all_done
        truncated = (self._steps >= self.max_steps) & ~terminated
        # Per-agent liveness AT this step (pre-reset), for value
        # bootstrapping: a dead-but-frozen agent's final_obs belongs to a
        # ghost sub-episode and must not be bootstrapped.
        self.last_alive = {aid: self._alive[aid].copy() for aid in self.agent_ids}
        done_idx = np.nonzero(terminated | truncated)[0]
        if len(done_idx):
            for aid in self.agent_ids:
                self.completed[aid].extend(self._ep_return[aid][done_idx].tolist())
                self._ep_return[aid][done_idx] = 0.0
                self._alive[aid][done_idx] = True
                self._envs[aid]._reset_indices(done_idx)
            self._steps[done_idx] = 0
        return final_obs, rewards, terminated, truncated

    def current_obs(self):
        return {aid: env.current_obs() for aid, env in self._envs.items()}

    def drain_episode_returns(self):
        out = self.completed
        self.completed = {aid: [] for aid in self.agent_ids}
        return out


class MultiAgentEnvRunner:
    """Rollout actor over a multi-agent env: one policy call PER AGENT per
    step (each a full [N]-env batch), GAE per agent under ITS policy's
    value head, batches grouped by policy id for the learners
    (ray: rollout_worker.py multi-agent sample collection)."""

    def __init__(
        self,
        env_creator: Callable,
        num_envs: int,
        rollout_length: int,
        policy_mapping: Dict[str, str],
        *,
        gamma: float = 0.99,
        lam: float = 0.95,
        seed: int = 0,
        hidden=(64, 64),
    ):
        self.env: MultiAgentVectorEnv = env_creator(num_envs=num_envs, seed=seed)
        self.rollout_length = rollout_length
        self.policy_mapping = dict(policy_mapping)
        self.gamma, self.lam = gamma, lam
        self.policies: Dict[str, JaxPolicy] = {}
        for i, pid in enumerate(sorted(set(self.policy_mapping.values()))):
            aid = next(a for a, p in self.policy_mapping.items() if p == pid)
            self.policies[pid] = JaxPolicy(
                self.env.observation_size(aid),
                self.env.num_actions(aid),
                seed=seed + 7 * i,
                hidden=hidden,
            )
        self._obs = self.env.reset(seed=seed)

    def set_weights(self, weights: Dict[str, Any]) -> None:
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)

    def sample(self, weights: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        if weights is not None:
            self.set_weights(weights)
        T, N = self.rollout_length, self.env.num_envs
        agents = self.env.agent_ids
        bufs = {
            aid: {
                "obs": np.zeros((T, N, self.env.observation_size(aid)), np.float32),
                "act": np.zeros((T, N), np.int64),
                "logp": np.zeros((T, N), np.float32),
                "val": np.zeros((T, N), np.float32),
                "rew": np.zeros((T, N), np.float32),
            }
            for aid in agents
        }
        done_buf = np.zeros((T, N), dtype=bool)

        obs = self._obs
        for t in range(T):
            acts = {}
            for aid in agents:
                pol = self.policies[self.policy_mapping[aid]]
                a, lp, v = pol.compute_actions(obs[aid])
                b = bufs[aid]
                b["obs"][t], b["act"][t], b["logp"][t], b["val"][t] = (
                    obs[aid], a, lp, v
                )
                acts[aid] = a
            final_obs, rewards, terminated, truncated = self.env.step(acts)
            if truncated.any():
                # Time-limit cutoffs bootstrap each agent's OWN value of
                # its final observation (same GAE reasoning as the
                # single-agent runner, env_runner.py): without it, good
                # policies that reach the cap learn V(late state) ~ 0.
                # Only for agents still ALIVE at the cutoff — a dead
                # agent's final_obs is a ghost sub-episode state and a
                # bootstrap there injects phantom return.
                alive = getattr(self.env, "last_alive", None)
                for aid in agents:
                    mask = truncated if alive is None else (truncated & alive[aid])
                    idx = np.nonzero(mask)[0]
                    if not len(idx):
                        continue
                    pol = self.policies[self.policy_mapping[aid]]
                    _, _, v_fin = pol.compute_actions(final_obs[aid])
                    rew = rewards[aid].copy()
                    rew[idx] += self.gamma * v_fin[idx]
                    rewards[aid] = rew
            for aid in agents:
                bufs[aid]["rew"][t] = rewards[aid]
            done_buf[t] = terminated | truncated
            obs = self.env.current_obs()
        self._obs = obs

        # Per-policy batches: each agent post-processes GAE under its own
        # policy's bootstrap, then batches concat per policy id.
        per_policy: Dict[str, List[SampleBatch]] = {}
        for aid in agents:
            pid = self.policy_mapping[aid]
            pol = self.policies[pid]
            _, _, last_v = pol.compute_actions(obs[aid])
            b = bufs[aid]
            adv, rets = compute_gae(
                b["rew"], b["val"], done_buf, last_v, self.gamma, self.lam
            )
            per_policy.setdefault(pid, []).append(
                SampleBatch(
                    {
                        OBS: b["obs"].reshape(T * N, -1),
                        ACTIONS: b["act"].reshape(-1),
                        LOGPS: b["logp"].reshape(-1),
                        ADVANTAGES: adv.reshape(-1),
                        RETURNS: rets.reshape(-1),
                    }
                )
            )
        return {
            "batches": {
                pid: dict(SampleBatch.concat_samples(bs))
                for pid, bs in per_policy.items()
            },
            "episode_returns": self.env.drain_episode_returns(),
            "steps": T * N * len(agents),
        }

    def ping(self) -> str:
        return "pong"


class MultiAgentPPOConfig:
    """Builder config (ray: AlgorithmConfig.multi_agent(policies=...,
    policy_mapping_fn=...))."""

    def __init__(self):
        self.env_creator: Optional[Callable] = None
        self.policy_mapping: Dict[str, str] = {}
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_length = 32
        self.gamma = 0.99
        self.lam = 0.95
        self.lr = 1e-3
        self.clip_param = 0.2
        self.entropy_coeff = 5e-3
        self.vf_coeff = 0.5
        self.num_epochs = 8
        self.minibatch_size = 128
        self.hidden = (64, 64)
        self.seed = 0

    def environment(self, env_creator: Callable) -> "MultiAgentPPOConfig":
        self.env_creator = env_creator
        return self

    def multi_agent(self, policy_mapping: Dict[str, str]) -> "MultiAgentPPOConfig":
        """policy_mapping: agent_id -> policy_id.  Agents sharing a policy
        id train ONE set of params on their pooled experience."""
        self.policy_mapping = dict(policy_mapping)
        return self

    def env_runners(
        self, num_env_runners: int = 2, num_envs_per_runner: int = 8,
        rollout_length: int = 32,
    ) -> "MultiAgentPPOConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_runner
        self.rollout_length = rollout_length
        return self

    def training(self, **kw) -> "MultiAgentPPOConfig":
        for k, v in kw.items():
            if not hasattr(self, k) or k in ("env_creator", "policy_mapping"):
                raise TypeError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, seed: int = 0) -> "MultiAgentPPOConfig":
        self.seed = seed
        return self

    def build(self) -> "MultiAgentPPO":
        if self.env_creator is None or not self.policy_mapping:
            raise ValueError("set .environment(creator) and .multi_agent(mapping)")
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """Multi-policy PPO: one fused-scan PPO learner PER policy id; shared
    policies train on the pooled batch of all their agents
    (ray: Algorithm with a PolicyMap of per-policy torch optimizers —
    here each policy's whole epoch loop is one jitted program)."""

    def __init__(self, config: MultiAgentPPOConfig):
        from ray_tpu.rllib.ppo import PPOConfig, _make_learner

        self.config = config
        ray_tpu.init(ignore_reinit_error=True)
        probe = config.env_creator(num_envs=1, seed=0)
        self.policy_ids = sorted(set(config.policy_mapping.values()))

        # Per-policy learners (PPO's fused epoch x minibatch scan).
        pc = PPOConfig()
        for k in (
            "gamma", "lam", "lr", "clip_param", "entropy_coeff", "vf_coeff",
            "num_epochs", "minibatch_size", "hidden",
        ):
            setattr(pc, k, getattr(config, k))
        self._learners = {}
        self._states = {}
        for i, pid in enumerate(self.policy_ids):
            aid = next(
                a for a, p in config.policy_mapping.items() if p == pid
            )
            init_state, update = _make_learner(
                pc, probe.observation_size(aid), probe.num_actions(aid)
            )
            self._learners[pid] = update
            self._states[pid] = init_state(config.seed + 13 * i)

        RunnerActor = ray_tpu.remote(MultiAgentEnvRunner)
        self.runners = [
            RunnerActor.remote(
                config.env_creator,
                config.num_envs_per_runner,
                config.rollout_length,
                config.policy_mapping,
                gamma=config.gamma,
                lam=config.lam,
                seed=config.seed + 1000 * (i + 1),
                hidden=config.hidden,
            )
            for i in range(config.num_env_runners)
        ]
        ray_tpu.get([r.ping.remote() for r in self.runners], timeout=120)
        self.iteration = 0
        self._total_steps = 0
        self._episode_returns: Dict[str, List[float]] = {}

    def get_weights(self) -> Dict[str, Any]:
        import jax

        return {
            pid: jax.tree_util.tree_map(np.asarray, st["params"])
            for pid, st in self._states.items()
        }

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        weights_ref = ray_tpu.put(self.get_weights())
        results = ray_tpu.get(
            [r.sample.remote(weights_ref) for r in self.runners], timeout=300
        )
        steps = 0
        merged: Dict[str, List[SampleBatch]] = {}
        for r in results:
            steps += r["steps"]
            for pid, b in r["batches"].items():
                merged.setdefault(pid, []).append(SampleBatch(b))
            for aid, rets in r["episode_returns"].items():
                self._episode_returns.setdefault(aid, []).extend(rets)
        for aid in self._episode_returns:
            self._episode_returns[aid] = self._episode_returns[aid][-100:]
        self._total_steps += steps

        metrics: Dict[str, Any] = {}
        for pid, batches in merged.items():
            batch = SampleBatch.concat_samples(batches)
            device_batch = {
                k: jnp.asarray(batch[k])
                for k in (OBS, ACTIONS, LOGPS, ADVANTAGES, RETURNS)
            }
            self._states[pid], m = self._learners[pid](
                self._states[pid], device_batch
            )
            metrics[f"{pid}/total_loss"] = float(m["total_loss"])
        self.iteration += 1
        reward_means = {
            f"{aid}/episode_reward_mean": (
                float(np.mean(rs)) if rs else 0.0
            )
            for aid, rs in self._episode_returns.items()
        }
        all_rets = [r for rs in self._episode_returns.values() for r in rs]
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(all_rets)) if all_rets else 0.0,
            "num_env_steps_sampled": self._total_steps,
            "env_steps_per_sec": steps / max(time.time() - t0, 1e-9),
            **reward_means,
            **metrics,
        }

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners = []
