"""JaxPolicy: actor-critic network + jitted inference/loss.

ray: rllib/policy/torch_policy_v2.py + core/rl_module/rl_module.py —
re-designed as pure-functional JAX: params are a pytree, inference is one
jitted batch call (`compute_actions`), and the PPO loss is a pure function
the learner differentiates.  No framework wrapper classes: functional
transforms ARE the abstraction.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def init_policy_params(
    key: jax.Array, obs_size: int, num_actions: int, hidden: Tuple[int, ...] = (64, 64)
) -> Dict[str, Any]:
    """MLP torso + separate policy/value heads (orthogonal init — the PPO
    baseline choice)."""

    def ortho(key, shape, scale):
        return jax.nn.initializers.orthogonal(scale)(key, shape)

    keys = jax.random.split(key, len(hidden) + 2)
    params = {"torso": [], "pi": None, "vf": None}
    sizes = (obs_size,) + hidden
    for i in range(len(hidden)):
        params["torso"].append(
            {
                "w": ortho(keys[i], (sizes[i], sizes[i + 1]), jnp.sqrt(2.0)),
                "b": jnp.zeros(sizes[i + 1]),
            }
        )
    params["pi"] = {
        "w": ortho(keys[-2], (sizes[-1], num_actions), 0.01),
        "b": jnp.zeros(num_actions),
    }
    params["vf"] = {"w": ortho(keys[-1], (sizes[-1], 1), 1.0), "b": jnp.zeros(1)}
    return params


def apply_policy(params: Dict[str, Any], obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs [B, obs_size] → (logits [B, A], value [B])."""
    h = obs
    for layer in params["torso"]:
        h = jnp.tanh(h @ layer["w"] + layer["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


@jax.jit
def _sample_actions(params, obs, key):
    logits, value = apply_policy(params, obs)
    action = jax.random.categorical(key, logits)
    logp = jax.nn.log_softmax(logits)
    logp_a = jnp.take_along_axis(logp, action[:, None], axis=1)[:, 0]
    return action, logp_a, value


class JaxPolicy:
    """Stateful convenience wrapper used by env runners: params + rng.

    `module` plugs a custom RLModule architecture in (ray:
    rl_module.py); None keeps the built-in MLP fast path (a module-level
    jit shared across instances)."""

    def __init__(self, obs_size: int, num_actions: int, seed: int = 0,
                 hidden=(64, 64), module=None):
        self.obs_size = obs_size
        self.num_actions = num_actions
        self.module = module
        key = jax.random.PRNGKey(seed)
        self._key, init_key = jax.random.split(key)
        if module is None:
            self.params = init_policy_params(init_key, obs_size, num_actions, hidden)
            self._sample = _sample_actions
        else:
            self.params = module.init(init_key, obs_size, num_actions)
            fwd = module.forward

            @jax.jit
            def _sample(params, obs, key):
                logits, value = fwd(params, obs)
                action = jax.random.categorical(key, logits)
                logp = jax.nn.log_softmax(logits)
                logp_a = jnp.take_along_axis(logp, action[:, None], axis=1)[:, 0]
                return action, logp_a, value

            self._sample = _sample

    def set_weights(self, params) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, params)

    def get_weights(self):
        return jax.tree_util.tree_map(np.asarray, self.params)

    def compute_actions(self, obs: np.ndarray):
        """Batch inference: [N, obs] → (actions [N], logp [N], values [N])."""
        self._key, sub = jax.random.split(self._key)
        a, lp, v = self._sample(self.params, jnp.asarray(obs), sub)
        return np.asarray(a), np.asarray(lp), np.asarray(v)
