"""PPO: config, jitted learner, and the Algorithm driving rollout actors.

ray: rllib/algorithms/ppo/ppo.py:335,376 (PPO.training_step),
core/learner/learner.py:89 (loss/update split), learner_group.py:43.
TPU-first: the learner's epoch×minibatch SGD loop is ONE jitted
lax.scan program — minibatching, loss, grads, and optimizer updates all
fuse into a single XLA computation per train iteration (the reference runs
a Python loop of torch forward/backcward per minibatch).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_vector_env
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    LOGPS,
    OBS,
    RETURNS,
    SampleBatch,
)


class PPOConfig:
    """Builder-style config (ray: rllib/algorithms/algorithm_config.py)."""

    def __init__(self):
        self.env: Optional[str | Callable] = None
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_length = 64
        self.gamma = 0.99
        self.lam = 0.95
        self.lr = 3e-4
        self.clip_param = 0.2
        self.entropy_coeff = 0.01
        self.vf_coeff = 0.5
        self.num_epochs = 4
        self.minibatch_size = 256
        self.hidden = (64, 64)
        self.module = None  # RLModule override (ray: rl_module.py)
        self.seed = 0

    # -- builder sections (mirror the reference's fluent API) -------------
    def environment(self, env: str | Callable) -> "PPOConfig":
        self.env = env
        return self

    def env_runners(
        self, num_env_runners: int = 2, num_envs_per_runner: int = 8,
        rollout_length: int = 64,
    ) -> "PPOConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_runner
        self.rollout_length = rollout_length
        return self

    _TRAINING_KEYS = frozenset(
        {
            "gamma", "lam", "lr", "clip_param", "entropy_coeff", "vf_coeff",
            "num_epochs", "minibatch_size", "hidden",
        }
    )

    def training(self, **kw) -> "PPOConfig":
        for k, v in kw.items():
            if k not in self._TRAINING_KEYS:
                raise TypeError(
                    f"unknown PPO training option {k!r}; valid: "
                    f"{sorted(self._TRAINING_KEYS)}"
                )
            setattr(self, k, v)
        return self

    def rl_module(self, module) -> "PPOConfig":
        """Plug a custom RLModule (ray: core/rl_module/rl_module.py)."""
        self.module = module
        return self

    def debugging(self, seed: int = 0) -> "PPOConfig":
        self.seed = seed
        return self

    def build(self) -> "PPO":
        if self.env is None:
            raise ValueError("call .environment(env) first")
        return PPO(self)


def _make_learner(config: PPOConfig, obs_size: int, num_actions: int):
    """Build (init_state, update) — update is one jitted scan over
    epochs×minibatches."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.rl_module import MLPModule

    module = config.module or MLPModule(config.hidden)
    apply_policy = module.forward

    opt = optax.adam(config.lr)
    clip, ent_c, vf_c = config.clip_param, config.entropy_coeff, config.vf_coeff

    def init_state(seed: int):
        key = jax.random.PRNGKey(seed)
        params = module.init(key, obs_size, num_actions)
        return {"params": params, "opt_state": opt.init(params), "key": key}

    def loss_fn(params, mb):
        logits, values = apply_policy(params, mb[OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, mb[ACTIONS][:, None], axis=1)[:, 0]
        ratio = jnp.exp(logp - mb[LOGPS])
        adv = mb[ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg1 = ratio * adv
        pg2 = jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv
        pg_loss = -jnp.mean(jnp.minimum(pg1, pg2))
        vf_loss = jnp.mean((values - mb[RETURNS]) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
        total = pg_loss + vf_c * vf_loss - ent_c * entropy
        return total, (pg_loss, vf_loss, entropy)

    def update(state, batch):
        """batch: dict of [B] device arrays, B divisible into minibatches."""
        B = batch[ACTIONS].shape[0]
        mb_size = min(config.minibatch_size, B)
        n_mb = max(B // mb_size, 1)
        used = n_mb * mb_size

        def epoch_step(carry, key):
            params, opt_state = carry
            perm = jax.random.permutation(key, B)[:used]

            def mb_step(carry, idx):
                params, opt_state = carry
                mb = {k: v[idx] for k, v in batch.items()}
                (total, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, mb
                )
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (total, *aux)

            idxs = perm.reshape(n_mb, mb_size)
            (params, opt_state), metrics = jax.lax.scan(
                mb_step, (params, opt_state), idxs
            )
            return (params, opt_state), metrics

        key, *epoch_keys = jax.random.split(state["key"], config.num_epochs + 1)
        (params, opt_state), metrics = jax.lax.scan(
            epoch_step,
            (state["params"], state["opt_state"]),
            jnp.stack(epoch_keys),
        )
        out_metrics = {
            "total_loss": metrics[0].mean(),
            "policy_loss": metrics[1].mean(),
            "vf_loss": metrics[2].mean(),
            "entropy": metrics[3].mean(),
        }
        return {"params": params, "opt_state": opt_state, "key": key}, out_metrics

    return init_state, jax.jit(update, donate_argnums=(0,))


class PPO:
    """ray: Algorithm (algorithms/algorithm.py:145) — train() runs one
    iteration: broadcast weights → parallel sample → learner update."""

    def __init__(self, config: PPOConfig):
        self.config = config
        ray_tpu.init(ignore_reinit_error=True)
        probe = make_vector_env(config.env, 1, seed=0)
        if getattr(probe, "continuous", False):
            raise ValueError(
                f"{type(self).__name__} needs a discrete-action env; "
                "use SAC for continuous control"
            )
        self._obs_size = probe.observation_size
        self._num_actions = probe.num_actions
        init_state, self._update = _make_learner(
            config, self._obs_size, self._num_actions
        )
        self._state = init_state(config.seed)
        RunnerActor = ray_tpu.remote(EnvRunner)
        self.runners = [
            RunnerActor.remote(
                config.env,
                config.num_envs_per_runner,
                config.rollout_length,
                gamma=config.gamma,
                lam=config.lam,
                seed=config.seed + 1000 * (i + 1),
                hidden=config.hidden,
                module=config.module,
            )
            for i in range(config.num_env_runners)
        ]
        ray_tpu.get([r.ping.remote() for r in self.runners], timeout=120)
        self.iteration = 0
        self._total_steps = 0
        self._episode_returns: List[float] = []

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self._state["params"])

    def set_weights(self, weights) -> None:
        import jax.numpy as jnp
        import jax

        self._state["params"] = jax.tree_util.tree_map(jnp.asarray, weights)

    def train(self) -> Dict[str, Any]:
        """One training iteration (ray: Algorithm.step :730)."""
        t0 = time.time()
        weights_ref = ray_tpu.put(self.get_weights())
        results = ray_tpu.get(
            [r.sample.remote(weights_ref) for r in self.runners], timeout=300
        )
        batch = SampleBatch.concat_samples([SampleBatch(r["batch"]) for r in results])
        for r in results:
            self._episode_returns.extend(r["episode_returns"])
            self._total_steps += r["steps"]
        self._episode_returns = self._episode_returns[-100:]

        import jax.numpy as jnp

        device_batch = {
            k: jnp.asarray(batch[k]) for k in (OBS, ACTIONS, LOGPS, ADVANTAGES, RETURNS)
        }
        self._state, metrics = self._update(self._state, device_batch)
        self.iteration += 1
        mean_ret = float(np.mean(self._episode_returns)) if self._episode_returns else 0.0
        sample_time = time.time() - t0
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": mean_ret,
            "num_env_steps_sampled": self._total_steps,
            "env_steps_per_sec": batch.count / max(sample_time, 1e-9),
            **{k: float(v) for k, v in metrics.items()},
        }

    # -- checkpointing (ray: Algorithm.save/restore) ----------------------
    def save(self, path: Optional[str] = None) -> str:
        """Full learner state: params + optimizer moments + RNG key, so a
        restored run continues training exactly (not a weights-only resume
        that resets Adam bias correction)."""
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        host_state = jax.tree_util.tree_map(np.asarray, self._state)
        ckpt = Checkpoint.from_dict(
            {"learner_state": host_state, "iteration": self.iteration}
        )
        return ckpt.to_directory(path)

    def restore(self, path: str) -> None:
        import jax
        import jax.numpy as jnp

        from ray_tpu.air.checkpoint import Checkpoint

        d = Checkpoint.from_directory(path).to_dict()
        self._state = jax.tree_util.tree_map(jnp.asarray, d["learner_state"])
        self.iteration = d["iteration"]

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners = []
