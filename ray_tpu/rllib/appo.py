"""APPO: asynchronous PPO (IMPALA architecture + clipped surrogate).

ray: rllib/algorithms/appo/appo.py — the reference's APPO runs PPO's
clipped-surrogate objective on IMPALA's asynchronous actor-learner
machinery, with V-trace correcting the sampling lag.  Here that is
literally the composition: APPO IS the IMPALA pipeline with the
learner's policy loss swapped for the PPO clip applied to V-trace
advantages (make_impala_learner's pg_loss_fn hook — one expression of
difference, zero duplicated machinery).
"""

from __future__ import annotations

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, make_impala_learner


class APPOConfig(IMPALAConfig):
    """IMPALA's knobs + the PPO clip (ray: appo.py APPOConfig).  Note
    vf_coeff keeps IMPALA's small default (0.01): advantages are
    standardized while V-trace value targets are raw returns — a large
    vf weight lets value gradients crush the shared torso (measured:
    0.5 plateaus CartPole at ~65 reward; 0.01 clears 130)."""

    def __init__(self):
        super().__init__()
        self.clip_param = 0.2

    _TRAINING_KEYS = IMPALAConfig._TRAINING_KEYS | {"clip_param"}

    def build(self) -> "APPO":
        if self.env is None:
            raise ValueError("call .environment(env) first")
        return APPO(self)


def make_appo_learner(config: APPOConfig, obs_size: int, num_actions: int):
    """IMPALA's V-trace learner with the PPO clipped surrogate as the
    policy objective (ray: appo_torch_policy's surrogate over vtrace;
    the behavior policy's logp is the ratio denominator)."""
    import jax.numpy as jnp

    clip = config.clip_param

    def clipped_surrogate(logp, behavior_logp, adv):
        ratio = jnp.exp(logp - behavior_logp)
        pg1 = ratio * adv
        pg2 = jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv
        return -jnp.mean(jnp.minimum(pg1, pg2))

    return make_impala_learner(
        config, obs_size, num_actions, pg_loss_fn=clipped_surrogate
    )


class APPO(IMPALA):
    """IMPALA's async pipeline, PPO's objective (ray: appo.py APPO)."""

    _make_learner = staticmethod(make_appo_learner)
