"""Environments: native vectorized envs for JAX-first RL.

ray: rllib/env/vector_env.py + gym registration.  TPU-first difference:
envs are BATCHED from the start — a VectorEnv steps N copies with numpy
vector math, so policy inference is one jitted batch call per step instead
of N scalar calls (the reference loops Python envs one by one in
evaluation/sampler.py).

CartPole dynamics follow the classic control problem definition (public
domain physics; same constants as the canonical gym task) implemented
natively — no gym dependency.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np


class VectorEnv:
    """Interface: batched reset/step over num_envs copies.

    Auto-reset semantics: when an env terminates, step() returns the
    terminal transition (done=True) and the NEXT observation is the reset
    state — the convention GAE bootstrapping expects."""

    num_envs: int
    observation_size: int
    num_actions: int

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(
        self, actions: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """actions [N] int → (final_obs [N, obs_size], rewards [N],
        terminated [N], truncated [N]).

        final_obs is the PRE-reset observation — callers bootstrap
        V(final_obs) for truncated (time-limit) episodes, which are not
        true terminations (the gym terminated/truncated split exists for
        exactly this GAE distinction)."""
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """N independent CartPole-v1 instances, vectorized in numpy."""

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5  # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500  # v1 episode cap

    num_actions = 2
    observation_size = 4

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), dtype=np.float64)
        self._steps = np.zeros(num_envs, dtype=np.int64)
        self._episode_return = np.zeros(num_envs, dtype=np.float64)
        self.completed_episode_returns: list = []

    def _reset_indices(self, idx: np.ndarray) -> None:
        self._state[idx] = self._rng.uniform(-0.05, 0.05, size=(len(idx), 4))
        self._steps[idx] = 0
        self._episode_return[idx] = 0.0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_indices(np.arange(self.num_envs))
        return self._state.astype(np.float32).copy()

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(np.asarray(actions) == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1
        self._episode_return += 1.0

        terminated = (np.abs(x) > self.X_LIMIT) | (np.abs(theta) > self.THETA_LIMIT)
        truncated = (self._steps >= self.MAX_STEPS) & ~terminated
        rewards = np.ones(self.num_envs, dtype=np.float32)
        final_obs = self._state.astype(np.float32).copy()
        done_idx = np.nonzero(terminated | truncated)[0]
        if len(done_idx):
            self.completed_episode_returns.extend(
                self._episode_return[done_idx].tolist()
            )
            self._reset_indices(done_idx)
        return final_obs, rewards, terminated, truncated

    def current_obs(self) -> np.ndarray:
        """Post-auto-reset observations (what the policy sees next step)."""
        return self._state.astype(np.float32).copy()

    def drain_episode_returns(self) -> list:
        out = self.completed_episode_returns
        self.completed_episode_returns = []
        return out


class PendulumVectorEnv(VectorEnv):
    """N independent Pendulum-v1 instances (classic control swing-up,
    public-domain physics), vectorized in numpy.  CONTINUOUS action
    space: torque in [-max_torque, max_torque], action_size 1 — the
    continuous-control counterpart CartPole can't provide (SAC's test
    bed).  Episodes are pure time-limit truncations (no termination)."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    num_actions = 1  # action_size for continuous envs
    action_size = 1
    continuous = True
    observation_size = 3  # (cos th, sin th, th_dot)

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self._th = np.zeros(num_envs)
        self._thdot = np.zeros(num_envs)
        self._steps = np.zeros(num_envs, dtype=np.int64)
        self._episode_return = np.zeros(num_envs)
        self.completed_episode_returns: list = []

    def _obs(self) -> np.ndarray:
        return np.stack(
            [np.cos(self._th), np.sin(self._th), self._thdot], axis=1
        ).astype(np.float32)

    def _reset_indices(self, idx: np.ndarray) -> None:
        self._th[idx] = self._rng.uniform(-np.pi, np.pi, size=len(idx))
        self._thdot[idx] = self._rng.uniform(-1.0, 1.0, size=len(idx))
        self._steps[idx] = 0
        self._episode_return[idx] = 0.0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_indices(np.arange(self.num_envs))
        return self._obs()

    def step(self, actions: np.ndarray):
        u = np.clip(
            np.asarray(actions, dtype=np.float64).reshape(self.num_envs),
            -self.MAX_TORQUE,
            self.MAX_TORQUE,
        )
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        costs = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        thdot = thdot + (
            3.0 * self.G / (2.0 * self.L) * np.sin(th)
            + 3.0 / (self.M * self.L**2) * u
        ) * self.DT
        thdot = np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED)
        th = th + thdot * self.DT
        self._th, self._thdot = th, thdot
        self._steps += 1
        rewards = (-costs).astype(np.float32)
        self._episode_return += rewards

        final_obs = self._obs()
        terminated = np.zeros(self.num_envs, dtype=bool)
        truncated = self._steps >= self.MAX_STEPS
        done_idx = np.nonzero(truncated)[0]
        if len(done_idx):
            self.completed_episode_returns.extend(
                self._episode_return[done_idx].tolist()
            )
            self._reset_indices(done_idx)
        return final_obs, rewards, terminated, truncated

    def current_obs(self) -> np.ndarray:
        return self._obs()

    def drain_episode_returns(self) -> list:
        out = self.completed_episode_returns
        self.completed_episode_returns = []
        return out


_ENV_REGISTRY: Dict[str, Callable[..., VectorEnv]] = {
    "CartPole-v1": CartPoleVectorEnv,
    "Pendulum-v1": PendulumVectorEnv,
}


def register_env(name: str, creator: Callable[..., VectorEnv]) -> None:
    """ray: tune.register_env — creator(num_envs, seed) -> VectorEnv."""
    _ENV_REGISTRY[name] = creator


def make_vector_env(env: str | Callable, num_envs: int, seed: int = 0) -> VectorEnv:
    if callable(env):
        return env(num_envs=num_envs, seed=seed)
    if env in _ENV_REGISTRY:
        return _ENV_REGISTRY[env](num_envs=num_envs, seed=seed)
    raise ValueError(f"unknown env {env!r}; register it with register_env()")
