"""RLModule: the pluggable model abstraction.

ray: rllib/core/rl_module/rl_module.py — the reference's new-stack module
API lets users swap network architectures into any algorithm.  JAX-first
redesign: a module is a pair of PURE functions — `init(key, obs_size,
num_actions) -> params` and `forward(params, obs) -> (logits, value)` —
so algorithms jit/grad/shard straight through it; no framework wrapper
object holds state.  Modules must be cloudpickle-able (they ride task
specs to env-runner actors).

Built-ins:
  * MLPModule        — tanh MLP torso + categorical policy / value heads
                       (the default every algorithm uses);
  * ContinuousMLPModule — squashed-Gaussian policy + twin Q heads (SAC).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple


class RLModule:
    """Interface (ray: rl_module.py RLModule): subclass and override
    init/forward to plug a custom architecture into PPO/IMPALA/APPO
    via config.rl_module(module=...)."""

    def init(self, key, obs_size: int, num_actions: int) -> Dict[str, Any]:
        raise NotImplementedError

    def forward(self, params: Dict[str, Any], obs):
        """obs [B, obs_size] -> (logits [B, A], value [B])."""
        raise NotImplementedError


class MLPModule(RLModule):
    """Default actor-critic MLP (orthogonal init, tanh activations)."""

    def __init__(self, hidden: Tuple[int, ...] = (64, 64)):
        self.hidden = tuple(hidden)

    def init(self, key, obs_size: int, num_actions: int) -> Dict[str, Any]:
        from ray_tpu.rllib.policy import init_policy_params

        return init_policy_params(key, obs_size, num_actions, self.hidden)

    def forward(self, params: Dict[str, Any], obs):
        from ray_tpu.rllib.policy import apply_policy

        return apply_policy(params, obs)


class ContinuousMLPModule(RLModule):
    """Squashed-Gaussian actor + twin Q critics for continuous control
    (SAC — ray: rllib/algorithms/sac's policy/Q model pair)."""

    def __init__(self, hidden: Tuple[int, ...] = (128, 128)):
        self.hidden = tuple(hidden)

    @staticmethod
    def _mlp_init(key, sizes, out, out_scale=1.0):
        import jax
        import jax.numpy as jnp

        keys = jax.random.split(key, len(sizes))
        layers = []
        dims = sizes + (out,)
        for i in range(len(dims) - 1):
            scale = jnp.sqrt(2.0) if i < len(dims) - 2 else out_scale
            layers.append(
                {
                    "w": jax.nn.initializers.orthogonal(scale)(
                        keys[i], (dims[i], dims[i + 1])
                    ),
                    "b": jnp.zeros(dims[i + 1]),
                }
            )
        return layers

    @staticmethod
    def _mlp_apply(layers, x):
        import jax.numpy as jnp

        for i, l in enumerate(layers):
            x = x @ l["w"] + l["b"]
            if i < len(layers) - 1:
                x = jnp.tanh(x)
        return x

    def init(self, key, obs_size: int, act_size: int) -> Dict[str, Any]:
        import jax

        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        sizes = (obs_size,) + self.hidden
        q_sizes = (obs_size + act_size,) + self.hidden
        return {
            "pi": self._mlp_init(k_pi, sizes, 2 * act_size, 0.01),
            "q1": self._mlp_init(k_q1, q_sizes, 1),
            "q2": self._mlp_init(k_q2, q_sizes, 1),
        }

    def pi(self, params, obs):
        """-> (mean [B, A], log_std [B, A])."""
        import jax.numpy as jnp

        out = self._mlp_apply(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        return mean, jnp.clip(log_std, -20.0, 2.0)

    def q(self, params, obs, act):
        import jax.numpy as jnp

        x = jnp.concatenate([obs, act], axis=-1)
        q1 = self._mlp_apply(params["q1"], x)[..., 0]
        q2 = self._mlp_apply(params["q2"], x)[..., 0]
        return q1, q2

    def forward(self, params, obs):  # actor-critic surface (unused by SAC)
        mean, log_std = self.pi(params, obs)
        return mean, log_std
