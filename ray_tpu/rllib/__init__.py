"""ray_tpu.rllib — reinforcement learning on the actor runtime.

ray: rllib/ — Algorithm over rollout-worker actors
(algorithms/algorithm.py:145, evaluation/rollout_worker.py:885) with the
new Learner stack (core/learner/learner.py:89).  TPU-first redesign:

- envs are vectorized from the start (one jitted policy call per step for
  the whole env batch, not per-env Python loops);
- the learner's epoch×minibatch SGD is ONE jitted lax.scan program;
- weights broadcast to runners as a single object-store put per iteration.

PPO is the flagship algorithm (CartPole learning smoke test in
tests/test_rllib.py mirrors the reference's --as-test reward-threshold
pattern).
"""

from ray_tpu.rllib.env import (
    CartPoleVectorEnv,
    PendulumVectorEnv,
    VectorEnv,
    make_vector_env,
    register_env,
)
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.policy import JaxPolicy, apply_policy, init_policy_params
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, LearnerGroup, vtrace
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.sac import SAC, SACConfig
from ray_tpu.rllib.rl_module import ContinuousMLPModule, MLPModule, RLModule
from ray_tpu.rllib.multi_agent import (
    MultiAgentCartPole,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
    MultiAgentVectorEnv,
)
from ray_tpu.rllib.offline import OfflineData, write_experiences
from ray_tpu.rllib.policy_client import PolicyClient, PolicyServer
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae

__all__ = [
    "PendulumVectorEnv",
    "APPO",
    "APPOConfig",
    "SAC",
    "SACConfig",
    "RLModule",
    "MLPModule",
    "ContinuousMLPModule",
    "CartPoleVectorEnv",
    "DQN",
    "DQNConfig",
    "EnvRunner",
    "IMPALA",
    "IMPALAConfig",
    "JaxPolicy",
    "LearnerGroup",
    "MultiAgentCartPole",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "MultiAgentVectorEnv",
    "OfflineData",
    "PPO",
    "PPOConfig",
    "PolicyClient",
    "PolicyServer",
    "SampleBatch",
    "VectorEnv",
    "apply_policy",
    "compute_gae",
    "init_policy_params",
    "make_vector_env",
    "register_env",
    "vtrace",
    "write_experiences",
]
