"""External-env policy serving: client/server action round-trips over TCP.

ray: rllib/env/policy_client.py:58 + policy_server_input.py — environments
that CANNOT be stepped by the framework (simulators behind their own
process/machine boundary, live systems) drive the loop themselves: they
request actions from a PolicyServer and log rewards back; the server
assembles the resulting transitions into training input.

Wire protocol: authkey-authenticated multiprocessing.connection (the same
transport the rest of the control plane uses), one request tuple per
round-trip.  Inference runs the algorithm's current weights server-side;
completed transitions accumulate in a thread-safe buffer the training loop
drains (the analogue of PolicyServerInput feeding an algorithm's sampler).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class PolicyServer:
    """Serves get_action/log_returns/end_episode to external envs.

    `compute_action(obs, explore) -> int` is the inference hook (the
    algorithm's current policy, e.g. DQN.compute_single_action).
    """

    def __init__(self, compute_action, host: str = "127.0.0.1", port: int = 0,
                 authkey: bytes = b"raytpu-policy"):
        from multiprocessing.connection import Listener

        self._compute = compute_action
        self._authkey = authkey
        self._listener = Listener((host, port), backlog=16, authkey=authkey)
        self.address: Tuple[str, int] = (host, self._listener.address[1])
        self._lock = threading.Lock()
        self._episodes: Dict[str, dict] = {}
        self._eid = 0
        self._transitions: List[tuple] = []
        self._shutdown = False
        threading.Thread(
            target=self._accept_loop, daemon=True, name="policy-server"
        ).start()

    # -- experience intake ---------------------------------------------------

    def _record(self, ep: dict, next_obs, done: float) -> None:
        self._transitions.append(
            (ep["obs"], ep["action"], ep["reward"], next_obs, done)
        )
        ep["obs"] = None
        ep["reward"] = 0.0

    def samples_ready(self) -> int:
        with self._lock:
            return len(self._transitions)

    def drain(self) -> Optional[Dict[str, np.ndarray]]:
        """Completed transitions as a columnar batch (feed it to a replay
        buffer: buffer.add_batch(**drain()) — the PolicyServerInput role)."""
        with self._lock:
            if not self._transitions:
                return None
            ts = self._transitions
            self._transitions = []
        obs, actions, rewards, next_obs, dones = zip(*ts)
        return {
            "obs": np.asarray(obs, np.float32),
            "actions": np.asarray(actions, np.int64),
            "rewards": np.asarray(rewards, np.float32),
            "next_obs": np.asarray(next_obs, np.float32),
            "dones": np.asarray(dones, np.float32),
        }

    # -- wire ----------------------------------------------------------------

    def _accept_loop(self) -> None:
        from ray_tpu._private.wire import wrap

        while not self._shutdown:
            try:
                # wire-framed like every other control conn (the client
                # connects through the same versioned transport).
                conn = wrap(self._listener.accept())
            except (OSError, EOFError):
                if self._shutdown:
                    return
                continue
            except Exception:
                continue  # failed auth handshake from a stranger
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn) -> None:
        while True:
            try:
                msg = conn.recv()
            except (OSError, EOFError):
                try:
                    conn.close()
                except OSError:
                    pass
                return
            try:
                out = self._handle(msg)
            except Exception as e:  # noqa: BLE001 — a failing inference
                # hook (bad obs shape, jax error) must surface to the
                # client as an error reply, not kill this thread and hang
                # the external env inside a recv with no timeout.
                out = ("error", f"{type(e).__name__}: {e}")
            try:
                conn.send(out)
            except (OSError, EOFError):
                return

    def _handle(self, msg: tuple):
        kind = msg[0]
        with self._lock:
            if kind == "start_episode":
                self._eid += 1
                eid = f"ep-{self._eid}"
                self._episodes[eid] = {"obs": None, "action": None, "reward": 0.0}
                return eid
            ep = self._episodes.get(msg[1])
            if ep is None:
                return ("error", f"unknown episode {msg[1]}")
            if kind == "get_action":
                obs = np.asarray(msg[2], np.float32)
                if ep["obs"] is not None:
                    self._record(ep, obs, 0.0)
            elif kind == "log_returns":
                ep["reward"] += float(msg[2])
                return "ok"
            elif kind == "end_episode":
                if ep["obs"] is not None:
                    # truncated episodes bootstrap (done=0): a time-limit
                    # cut is not a terminal state (same convention as the
                    # internal runners' `terminated`-only done flag).
                    truncated = bool(msg[3]) if len(msg) > 3 else False
                    self._record(
                        ep, np.asarray(msg[2], np.float32),
                        0.0 if truncated else 1.0,
                    )
                self._episodes.pop(msg[1], None)
                return "ok"
            else:
                return ("error", f"unknown request {kind!r}")
        # get_action inference runs OUTSIDE the lock: one slow forward (or
        # the first-call jit compile) must not stall every other client's
        # round-trip or the trainer's drain().  Episodes are driven
        # sequentially by their own env process, so the unlocked window
        # cannot interleave two actions of one episode.
        action = int(self._compute(obs, bool(msg[3])))
        with self._lock:
            ep["obs"], ep["action"] = obs, action
        return action

    def close(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        except OSError:
            pass


class PolicyClient:
    """Driven by the external environment process
    (ray: rllib/env/policy_client.py:58 — same four-call surface)."""

    def __init__(self, address: Tuple[str, int],
                 authkey: bytes = b"raytpu-policy", timeout: float = 30.0):
        from ray_tpu._private.object_plane import _connect_with_deadline

        self._conn = _connect_with_deadline(tuple(address), authkey, timeout)
        # Request/response serialization on the one conn — a dedicated
        # wire lock (named for the concurrency lint's idiom exemption).
        self._conn_lock = threading.Lock()

    def _call(self, *msg):
        with self._conn_lock:
            self._conn.send(msg)
            out = self._conn.recv()
        if isinstance(out, tuple) and out and out[0] == "error":
            raise RuntimeError(out[1])
        return out

    def start_episode(self) -> str:
        return self._call("start_episode", None)

    def get_action(self, episode_id: str, observation, explore: bool = True) -> int:
        return self._call("get_action", episode_id, np.asarray(observation), explore)

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._call("log_returns", episode_id, float(reward))

    def end_episode(self, episode_id: str, observation,
                    truncated: bool = False) -> None:
        """truncated=True marks a time-limit cut (the final transition
        bootstraps rather than terminating)."""
        self._call("end_episode", episode_id, np.asarray(observation), truncated)

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass
