"""IMPALA: async actor-learner RL with V-trace off-policy correction.

ray: rllib/algorithms/impala/impala.py:478,620 (async sample queues feeding
a learner thread) + rllib/core/learner/learner_group.py:43 (multi-learner
DDP update).  TPU-first redesign:

- Env runners are plain actors that ALWAYS have a sample request in
  flight: the driver harvests whichever trajectory finishes first
  (`ray_tpu.wait`) and immediately resubmits to that runner, so sampling
  and learning overlap without a dedicated learner thread — the runtime's
  async task plane IS the sample queue.
- The off-policy lag this creates is corrected with V-trace (Espeholt et
  al. 2018, public algorithm) computed INSIDE the jitted update: one
  reverse `lax.scan` over time, fused with the loss/grad/optimizer step
  into a single XLA program.
- LearnerGroup is not N DDP actors exchanging NCCL grads: it is ONE jitted
  update pjit-sharded over a `learner` mesh axis (batch sharded on the env
  dimension, params replicated) — XLA inserts the gradient psum on ICI.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_vector_env
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.sample_batch import ACTIONS, LOGPS, OBS


def vtrace(
    target_logps,
    behavior_logps,
    rewards,
    values,
    next_values,
    terminateds,
    dones,
    *,
    gamma: float,
    rho_clip: float = 1.0,
    c_clip: float = 1.0,
):
    """V-trace targets + policy-gradient advantages over a [T, N] rollout.

    All inputs are [T, N] device arrays; `values`/`next_values` are the
    CURRENT policy's value estimates of obs/next_obs.  `terminateds` zeroes
    the bootstrap (true episode end); `dones` additionally cuts the
    correction trace at time-limit truncations, whose next_values still
    bootstrap.  Returns (vs [T, N], pg_advantages [T, N]).
    """
    import jax.numpy as jnp
    from jax import lax

    rhos = jnp.exp(target_logps - behavior_logps)
    clipped_rho = jnp.minimum(rho_clip, rhos)
    clipped_c = jnp.minimum(c_clip, rhos)
    term_f = terminateds.astype(values.dtype)
    done_f = dones.astype(values.dtype)
    discount = gamma * (1.0 - term_f)  # per-step bootstrap discount
    deltas = clipped_rho * (rewards + discount * next_values - values)

    def backward(carry, inp):
        vs_minus_v_next, vs_next = carry
        delta, disc, c, cont, v, nv, r, rho = inp
        vs_minus_v = delta + disc * cont * c * vs_minus_v_next
        vs = v + vs_minus_v
        # PG target: bootstrap through vs_{t+1} while the episode lives,
        # through V(next_obs) across a truncation, through nothing at a
        # true termination (disc already zero there).
        q = r + disc * jnp.where(cont > 0.0, vs_next, nv)
        adv = rho * (q - v)
        return (vs_minus_v, vs), (vs, adv)

    cont = 1.0 - done_f  # trace continues only when the episode does
    init = (jnp.zeros_like(values[-1]), next_values[-1])
    _, (vs, pg_adv) = lax.scan(
        backward,
        init,
        (deltas, discount, clipped_c, cont, values, next_values, rewards,
         clipped_rho),
        reverse=True,
    )
    return vs, pg_adv


class LearnerGroup:
    """Shard one jitted update over a `learner` mesh axis.

    ray: rllib/core/learner/learner_group.py:43,129 — the reference spawns
    learner ACTORS and all-reduces torch grads between them.  On TPU the
    idiomatic form is SPMD: the batch's env axis is sharded across the
    learner submesh, params stay replicated, and jit/XLA insert the
    gradient psum.  Semantics are bit-for-bit those of the unsharded
    program (tested: 1-learner vs 2-learner parity).
    """

    def __init__(self, update_fn: Callable, num_learners: int = 1):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devices = jax.devices()
        if num_learners > len(devices):
            raise ValueError(
                f"num_learners={num_learners} > available devices {len(devices)}"
            )
        self.num_learners = num_learners
        self.mesh = Mesh(np.array(devices[:num_learners]), ("learner",))
        self._replicated = NamedSharding(self.mesh, P())
        # Batch leaves are [T, N, ...] — shard the env axis (dim 1).
        self._batch_sharding = NamedSharding(self.mesh, P(None, "learner"))
        self._update = jax.jit(update_fn, donate_argnums=(0,))
        self._jax = jax

    def _place(self, tree, sharding):
        return self._jax.tree_util.tree_map(
            lambda x: self._jax.device_put(x, sharding), tree
        )

    def update(self, state, batch):
        for leaf in self._jax.tree_util.tree_leaves(batch):
            if leaf.shape[1] % self.num_learners:
                raise ValueError(
                    f"env axis {leaf.shape[1]} not divisible by "
                    f"num_learners={self.num_learners}"
                )
        state = self._place(state, self._replicated)
        batch = self._place(batch, self._batch_sharding)
        with self.mesh:
            return self._update(state, batch)


class IMPALAConfig:
    """Builder-style config (ray: rllib/algorithms/impala/impala.py:60)."""

    def __init__(self):
        self.env: Optional[str | Callable] = None
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_length = 16
        self.gamma = 0.99
        self.lr = 1e-3
        self.entropy_coeff = 3e-3
        # Small because pg advantages are standardized while V-trace value
        # targets are raw returns: a large vf weight lets value gradients
        # crush the shared torso (measured: vf_coeff 0.5 stalls CartPole at
        # ~40 reward; 0.01 solves it).
        self.vf_coeff = 0.01
        self.rho_clip = 1.0
        self.c_clip = 1.0
        self.num_learners = 1
        self.updates_per_iteration = 8
        self.broadcast_interval = 1  # weight refresh every N updates
        self.hidden = (64, 64)
        self.module = None  # RLModule override (ray: rl_module.py)
        self.seed = 0

    def environment(self, env: str | Callable) -> "IMPALAConfig":
        self.env = env
        return self

    def env_runners(
        self, num_env_runners: int = 2, num_envs_per_runner: int = 8,
        rollout_length: int = 16,
    ) -> "IMPALAConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_runner
        self.rollout_length = rollout_length
        return self

    _TRAINING_KEYS = frozenset(
        {
            "gamma", "lr", "entropy_coeff", "vf_coeff", "rho_clip", "c_clip",
            "num_learners", "updates_per_iteration", "broadcast_interval",
            "hidden",
        }
    )

    def training(self, **kw) -> "IMPALAConfig":
        for k, v in kw.items():
            if k not in self._TRAINING_KEYS:
                raise TypeError(
                    f"unknown IMPALA training option {k!r}; valid: "
                    f"{sorted(self._TRAINING_KEYS)}"
                )
            setattr(self, k, v)
        return self

    def rl_module(self, module) -> "IMPALAConfig":
        """Plug a custom RLModule (ray: core/rl_module/rl_module.py)."""
        self.module = module
        return self

    def debugging(self, seed: int = 0) -> "IMPALAConfig":
        self.seed = seed
        return self

    def build(self) -> "IMPALA":
        if self.env is None:
            raise ValueError("call .environment(env) first")
        return IMPALA(self)


def make_impala_learner(
    config: IMPALAConfig, obs_size: int, num_actions: int, pg_loss_fn=None
):
    """(init_state, update_fn): V-trace actor-critic update as one pure fn.

    ray: rllib/algorithms/impala/vtrace_torch_policy + learner.py:657 —
    here loss, V-trace scan, grads and the optimizer step all fuse into a
    single XLA program, shardable by LearnerGroup.

    pg_loss_fn(logp, behavior_logp, adv) -> scalar overrides the policy
    objective on the SAME V-trace machinery (APPO passes the PPO clipped
    surrogate; None = the plain V-trace policy gradient).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.rl_module import MLPModule

    module = config.module or MLPModule(config.hidden)
    apply_policy = module.forward

    opt = optax.adam(config.lr)
    ent_c, vf_c = config.entropy_coeff, config.vf_coeff

    def init_state(seed: int):
        key = jax.random.PRNGKey(seed)
        params = module.init(key, obs_size, num_actions)
        return {"params": params, "opt_state": opt.init(params)}

    def loss_fn(params, batch):
        T, N = batch[ACTIONS].shape
        obs = batch[OBS].reshape(T * N, obs_size)
        nobs = batch["next_obs"].reshape(T * N, obs_size)
        logits, values = apply_policy(params, obs)
        _, next_values = apply_policy(params, nobs)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch[ACTIONS].reshape(-1)[:, None], axis=1
        )[:, 0]

        vs, pg_adv = vtrace(
            jax.lax.stop_gradient(logp.reshape(T, N)),
            batch[LOGPS],
            batch["rewards"],
            jax.lax.stop_gradient(values.reshape(T, N)),
            jax.lax.stop_gradient(next_values.reshape(T, N)),
            batch["terminateds"],
            batch["dones"],
            gamma=config.gamma,
            rho_clip=config.rho_clip,
            c_clip=config.c_clip,
        )
        adv = pg_adv.reshape(-1)
        # Standardize advantages per batch: raw lambda=1 V-trace returns on
        # a small rollout swing over orders of magnitude, drowning the
        # entropy/value terms (same reasoning as PPO's normalization).
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        if pg_loss_fn is not None:
            pg_loss = pg_loss_fn(logp, batch[LOGPS].reshape(-1), adv)
        else:
            pg_loss = -jnp.mean(adv * logp)
        vf_loss = 0.5 * jnp.mean((values - vs.reshape(-1)) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
        total = pg_loss + vf_c * vf_loss - ent_c * entropy
        return total, (pg_loss, vf_loss, entropy)

    def update(state, batch):
        (total, (pg, vf, ent)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state["params"], batch)
        updates, opt_state = opt.update(grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        metrics = {
            "total_loss": total,
            "policy_loss": pg,
            "vf_loss": vf,
            "entropy": ent,
        }
        return {"params": params, "opt_state": opt_state}, metrics

    return init_state, update


class IMPALA:
    """Async actor-learner algorithm (ray: impala.py:620 training_step).

    Every runner permanently has one `sample_trajectory` task in flight;
    `train()` consumes whichever trajectories complete first, updates the
    learner on each, and resubmits with the freshest weights.  Sampling for
    update k+1 proceeds WHILE update k runs — the lag (tracked as
    `avg_weights_lag`) is what V-trace corrects.
    """

    _make_learner = staticmethod(make_impala_learner)

    def __init__(self, config: IMPALAConfig):
        self.config = config
        ray_tpu.init(ignore_reinit_error=True)
        probe = make_vector_env(config.env, 1, seed=0)
        if getattr(probe, "continuous", False):
            raise ValueError(
                f"{type(self).__name__} needs a discrete-action env; "
                "use SAC for continuous control"
            )
        self._obs_size = probe.observation_size
        self._num_actions = probe.num_actions
        init_state, update_fn = self._make_learner(
            config, self._obs_size, self._num_actions
        )
        self._learners = LearnerGroup(update_fn, config.num_learners)
        self._state = init_state(config.seed)
        self._weights_version = 0
        self._weights_ref = ray_tpu.put(self.get_weights())

        RunnerActor = ray_tpu.remote(EnvRunner)
        self.runners = [
            RunnerActor.remote(
                config.env,
                config.num_envs_per_runner,
                config.rollout_length,
                gamma=config.gamma,
                seed=config.seed + 1000 * (i + 1),
                hidden=config.hidden,
                module=config.module,
            )
            for i in range(config.num_env_runners)
        ]
        ray_tpu.get([r.ping.remote() for r in self.runners], timeout=120)
        # Prime the async pipeline: one request in flight per runner.
        self._inflight: Dict[Any, Any] = {
            r.sample_trajectory.remote(self._weights_ref, self._weights_version): r
            for r in self.runners
        }
        self.iteration = 0
        self._updates = 0
        self._total_steps = 0
        self._dead_runners = 0
        self._episode_returns: List[float] = []
        self._lags: List[int] = []

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self._state["params"])

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp

        self._state["params"] = jax.tree_util.tree_map(jnp.asarray, weights)

    def _harvest_one(self, timeout: float = 120.0):
        from ray_tpu.exceptions import ActorDiedError

        while True:
            if not self._inflight:
                raise RuntimeError(
                    f"all {self.config.num_env_runners} env runners have died"
                )
            ready, _ = ray_tpu.wait(
                list(self._inflight), num_returns=1, timeout=timeout
            )
            if not ready:
                raise TimeoutError("no trajectory completed within timeout")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            # Resubmit BEFORE the get: the completed ref's get can still
            # raise (user env error) and the runner must stay in the
            # pipeline either way — losing it would silently shrink the
            # pool until train() times out with no runners left.
            new_ref = runner.sample_trajectory.remote(
                self._weights_ref, self._weights_version
            )
            self._inflight[new_ref] = runner
            try:
                return ray_tpu.get(ref)
            except ActorDiedError:
                # The runner ACTOR is gone (crash/OOM-kill): drop it — its
                # resubmitted ref would error instantly and win every wait,
                # starving healthy runners forever (livelock). Training
                # degrades to the surviving pool (ray: the reference's
                # ignore_env_runner_failures degradation).
                self._inflight.pop(new_ref, None)
                self.runners = [r for r in self.runners if r is not runner]
                self._dead_runners += 1
                continue

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        steps = 0
        metrics = {}
        for _ in range(self.config.updates_per_iteration):
            result = self._harvest_one()
            self._episode_returns.extend(result["episode_returns"])
            self._total_steps += result["steps"]
            steps += result["steps"]
            self._lags.append(self._weights_version - result["weights_version"])

            # Numpy batch goes straight to LearnerGroup: its device_put does
            # the single host->sharded-devices transfer (a jnp.asarray here
            # would commit to device 0 first and reshard — two copies).
            self._state, metrics = self._learners.update(
                self._state, result["batch"]
            )
            self._updates += 1
            if self._updates % self.config.broadcast_interval == 0:
                self._weights_version += 1
                self._weights_ref = ray_tpu.put(self.get_weights())

        self._episode_returns = self._episode_returns[-100:]
        self._lags = self._lags[-200:]
        self.iteration += 1
        mean_ret = (
            float(np.mean(self._episode_returns)) if self._episode_returns else 0.0
        )
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": mean_ret,
            "num_env_steps_sampled": self._total_steps,
            "env_steps_per_sec": steps / max(time.time() - t0, 1e-9),
            "avg_weights_lag": float(np.mean(self._lags)) if self._lags else 0.0,
            "num_updates": self._updates,
            "num_dead_env_runners": self._dead_runners,
            **{k: float(v) for k, v in metrics.items()},
        }

    # -- checkpointing (ray: Algorithm.save/restore) ----------------------
    def save(self, path: Optional[str] = None) -> str:
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        host_state = jax.tree_util.tree_map(np.asarray, self._state)
        ckpt = Checkpoint.from_dict(
            {"learner_state": host_state, "iteration": self.iteration}
        )
        return ckpt.to_directory(path)

    def restore(self, path: str) -> None:
        import jax
        import jax.numpy as jnp

        from ray_tpu.air.checkpoint import Checkpoint

        d = Checkpoint.from_directory(path).to_dict()
        self._state = jax.tree_util.tree_map(jnp.asarray, d["learner_state"])
        self.iteration = d["iteration"]
        self._weights_version += 1
        self._weights_ref = ray_tpu.put(self.get_weights())

    def stop(self) -> None:
        self._inflight.clear()
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners = []
