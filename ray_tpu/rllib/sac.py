"""SAC: soft actor-critic for continuous control.

ray: rllib/algorithms/sac/sac.py — off-policy maximum-entropy RL with a
squashed-Gaussian actor, twin Q critics, polyak-averaged targets, and
automatic entropy-temperature tuning.  TPU-first: the whole update
(actor + both critics + alpha + target polyak) is ONE jitted program;
replay sampling stays host-side numpy; env runners are actors collecting
with the freshest actor params (same runner pattern as DQN).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_vector_env
from ray_tpu.rllib.rl_module import ContinuousMLPModule


class ContinuousReplayBuffer:
    """Numpy ring buffer with float action vectors (the DQN buffer stores
    int action ids; ray: replay_buffers/replay_buffer.py)."""

    def __init__(self, capacity: int, obs_size: int, act_size: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_size), dtype=np.float32)
        self.actions = np.zeros((capacity, act_size), dtype=np.float32)
        self.rewards = np.zeros(capacity, dtype=np.float32)
        self.next_obs = np.zeros((capacity, obs_size), dtype=np.float32)
        self.terminateds = np.zeros(capacity, dtype=np.float32)
        self.size = 0
        self._idx = 0

    def add_batch(self, obs, actions, rewards, next_obs, terminateds) -> None:
        n = len(obs)
        idx = (self._idx + np.arange(n)) % self.capacity
        self.obs[idx] = obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.next_obs[idx] = next_obs
        self.terminateds[idx] = terminateds
        self._idx = (self._idx + n) % self.capacity
        self.size = min(self.size + n, self.capacity)

    def sample(self, batch_size: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.size, size=batch_size)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "next_obs": self.next_obs[idx],
            "terminateds": self.terminateds[idx],
        }


class _SACRunner:
    """Actor payload: steps a continuous VectorEnv with the squashed-
    Gaussian actor (jitted batch inference)."""

    def __init__(self, env, num_envs: int, seed: int, hidden, act_limit: float):
        import jax

        self.env = make_vector_env(env, num_envs, seed=seed)
        self.module = ContinuousMLPModule(hidden)
        self.act_limit = act_limit
        self._key = jax.random.PRNGKey(seed)
        self._params = None
        mod, limit = self.module, act_limit

        @jax.jit
        def _act(params, obs, key):
            import jax.numpy as jnp

            mean, log_std = mod.pi(params, obs)
            eps = jax.random.normal(key, mean.shape)
            return jnp.tanh(mean + jnp.exp(log_std) * eps) * limit

        self._act = _act
        self._obs = self.env.reset(seed=seed)

    def collect(self, params, n_steps: int, random_actions: bool = False) -> Dict[str, Any]:
        import jax

        if params is not None:
            self._params = params
        N = self.env.num_envs
        cols = {k: [] for k in ("obs", "actions", "rewards", "next_obs", "terminateds")}
        obs = self._obs
        self._key, sub = jax.random.split(self._key)  # fresh per collect:
        # an unsplit key would replay the SAME warmup action sequence
        # every call, filling the buffer with correlated exploration
        rng = np.random.default_rng(int(jax.random.randint(sub, (), 0, 2**31 - 1)))
        for _ in range(n_steps):
            if random_actions or self._params is None:
                acts = rng.uniform(
                    -self.act_limit, self.act_limit,
                    size=(N, self.env.action_size),
                ).astype(np.float32)
            else:
                self._key, sub = jax.random.split(self._key)
                acts = np.asarray(self._act(self._params, obs, sub))
            final_obs, rewards, terminated, _trunc = self.env.step(acts)
            cols["obs"].append(obs)
            cols["actions"].append(acts)
            cols["rewards"].append(rewards)
            cols["next_obs"].append(final_obs)
            cols["terminateds"].append(terminated.astype(np.float32))
            obs = self.env.current_obs()
        self._obs = obs
        return {
            "batch": {k: np.concatenate(v, axis=0) for k, v in cols.items()},
            "episode_returns": self.env.drain_episode_returns(),
            "steps": n_steps * N,
        }

    def ping(self):
        return "pong"


class SACConfig:
    """Builder-style config (ray: sac.py SACConfig)."""

    def __init__(self):
        self.env: Optional[str | Callable] = None
        self.num_env_runners = 1
        self.num_envs_per_runner = 8
        self.rollout_length = 32
        self.gamma = 0.99
        self.lr = 3e-4
        self.tau = 0.005  # polyak
        self.batch_size = 256
        self.buffer_capacity = 100_000
        self.learning_starts = 1_000
        self.updates_per_iteration = 64
        self.act_limit = 2.0
        self.target_entropy: Optional[float] = None  # default: -act_size
        self.hidden = (128, 128)
        self.seed = 0

    def environment(self, env) -> "SACConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners=1, num_envs_per_runner=8,
                    rollout_length=32) -> "SACConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_runner
        self.rollout_length = rollout_length
        return self

    _TRAINING_KEYS = frozenset(
        {
            "gamma", "lr", "tau", "batch_size", "buffer_capacity",
            "learning_starts", "updates_per_iteration", "act_limit",
            "target_entropy", "hidden",
        }
    )

    def training(self, **kw) -> "SACConfig":
        for k, v in kw.items():
            if k not in self._TRAINING_KEYS:
                raise TypeError(
                    f"unknown SAC training option {k!r}; valid: "
                    f"{sorted(self._TRAINING_KEYS)}"
                )
            setattr(self, k, v)
        return self

    def debugging(self, seed: int = 0) -> "SACConfig":
        self.seed = seed
        return self

    def build(self) -> "SAC":
        if self.env is None:
            raise ValueError("call .environment(env) first")
        return SAC(self)


def make_sac_learner(config: SACConfig, obs_size: int, act_size: int):
    """(init_state, update): actor + twin critics + alpha + polyak, fused
    into one XLA program (ray: sac_torch_policy's three optimizers)."""
    import jax
    import jax.numpy as jnp
    import optax

    module = ContinuousMLPModule(config.hidden)
    limit = config.act_limit
    target_ent = (
        config.target_entropy if config.target_entropy is not None else -float(act_size)
    )
    pi_opt = optax.adam(config.lr)
    q_opt = optax.adam(config.lr)
    a_opt = optax.adam(config.lr)
    gamma, tau = config.gamma, config.tau

    def sample_action(params, obs, key):
        """Reparameterized squashed-Gaussian sample + log-prob with the
        tanh change-of-variables correction."""
        mean, log_std = module.pi(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape)
        pre = mean + std * eps
        act = jnp.tanh(pre)
        logp = (
            -0.5 * (((pre - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
        ).sum(-1)
        logp = logp - jnp.sum(jnp.log(1 - act**2 + 1e-6), axis=-1)
        return act * limit, logp

    def init_state(seed: int):
        key = jax.random.PRNGKey(seed)
        k_init, key = jax.random.split(key)
        params = module.init(k_init, obs_size, act_size)
        return {
            "params": params,
            "target": jax.tree_util.tree_map(jnp.array, params),
            "pi_opt": pi_opt.init(params["pi"]),
            "q_opt": q_opt.init({"q1": params["q1"], "q2": params["q2"]}),
            "log_alpha": jnp.zeros(()),
            "a_opt": a_opt.init(jnp.zeros(())),
            "key": key,
        }

    def update(state, batch):
        key, k_next, k_pi = jax.random.split(state["key"], 3)
        params, target = state["params"], state["target"]
        alpha = jnp.exp(state["log_alpha"])

        # -- critic ----------------------------------------------------
        next_act, next_logp = sample_action(params, batch["next_obs"], k_next)
        tq1, tq2 = module.q(target, batch["next_obs"], next_act)
        y = batch["rewards"] + gamma * (1.0 - batch["terminateds"]) * (
            jnp.minimum(tq1, tq2) - alpha * next_logp
        )
        y = jax.lax.stop_gradient(y)

        def q_loss_fn(q_params):
            p = {**params, **q_params}
            q1, q2 = module.q(p, batch["obs"], batch["actions"])
            return jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2)

        q_params = {"q1": params["q1"], "q2": params["q2"]}
        q_loss, q_grads = jax.value_and_grad(q_loss_fn)(q_params)
        q_updates, q_opt_state = q_opt.update(q_grads, state["q_opt"], q_params)
        q_params = optax.apply_updates(q_params, q_updates)
        params = {**params, **q_params}

        # -- actor -----------------------------------------------------
        def pi_loss_fn(pi_params):
            p = {**params, "pi": pi_params}
            act, logp = sample_action(p, batch["obs"], k_pi)
            q1, q2 = module.q(params, batch["obs"], act)
            return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

        (pi_loss, logp), pi_grads = jax.value_and_grad(
            pi_loss_fn, has_aux=True
        )(params["pi"])
        pi_updates, pi_opt_state = pi_opt.update(
            pi_grads, state["pi_opt"], params["pi"]
        )
        params = {**params, "pi": optax.apply_updates(params["pi"], pi_updates)}

        # -- temperature ----------------------------------------------
        def a_loss_fn(log_alpha):
            return -jnp.mean(
                jnp.exp(log_alpha) * jax.lax.stop_gradient(logp + target_ent)
            )

        a_loss, a_grad = jax.value_and_grad(a_loss_fn)(state["log_alpha"])
        a_updates, a_opt_state = a_opt.update(a_grad, state["a_opt"])
        log_alpha = optax.apply_updates(state["log_alpha"], a_updates)

        # -- polyak target --------------------------------------------
        target = jax.tree_util.tree_map(
            lambda t, p: (1.0 - tau) * t + tau * p, target, params
        )
        metrics = {
            "q_loss": q_loss,
            "pi_loss": pi_loss,
            "alpha": jnp.exp(log_alpha),
            "entropy": -jnp.mean(logp),
        }
        return {
            "params": params,
            "target": target,
            "pi_opt": pi_opt_state,
            "q_opt": q_opt_state,
            "log_alpha": log_alpha,
            "a_opt": a_opt_state,
            "key": key,
        }, metrics

    return init_state, jax.jit(update, donate_argnums=(0,))


class SAC:
    """ray: Algorithm surface (train/save/restore/get_weights) over the
    SAC learner + replay + runner actors."""

    def __init__(self, config: SACConfig):
        self.config = config
        ray_tpu.init(ignore_reinit_error=True)
        probe = make_vector_env(config.env, 1, seed=0)
        if not getattr(probe, "continuous", False):
            raise ValueError("SAC needs a continuous-action env (e.g. Pendulum-v1)")
        self._obs_size = probe.observation_size
        self._act_size = probe.action_size
        init_state, self._update = make_sac_learner(
            config, self._obs_size, self._act_size
        )
        self._state = init_state(config.seed)
        self.buffer = ContinuousReplayBuffer(
            config.buffer_capacity, self._obs_size, self._act_size
        )
        self._rng = np.random.default_rng(config.seed)
        Runner = ray_tpu.remote(_SACRunner)
        self.runners = [
            Runner.remote(
                config.env, config.num_envs_per_runner,
                config.seed + 1000 * (i + 1), config.hidden, config.act_limit,
            )
            for i in range(config.num_env_runners)
        ]
        ray_tpu.get([r.ping.remote() for r in self.runners], timeout=120)
        self.iteration = 0
        self._total_steps = 0
        self._episode_returns: List[float] = []

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self._state["params"])

    def train(self) -> Dict[str, Any]:
        import jax

        t0 = time.time()
        warmup = self._total_steps < self.config.learning_starts
        # Runners only run the actor: ship pi params alone (the twin Q
        # trees are the bulk of the bytes), and nothing during warmup.
        weights = None if warmup else {
            "pi": jax.tree_util.tree_map(np.asarray, self._state["params"]["pi"])
        }
        results = ray_tpu.get(
            [
                r.collect.remote(weights, self.config.rollout_length, warmup)
                for r in self.runners
            ],
            timeout=300,
        )
        steps = 0
        for res in results:
            b = res["batch"]
            self.buffer.add_batch(
                b["obs"], b["actions"], b["rewards"], b["next_obs"],
                b["terminateds"],
            )
            self._episode_returns.extend(res["episode_returns"])
            steps += res["steps"]
        self._total_steps += steps

        metrics: Dict[str, Any] = {}
        if self._total_steps >= self.config.learning_starts:
            for _ in range(self.config.updates_per_iteration):
                batch = self.buffer.sample(self.config.batch_size, self._rng)
                self._state, metrics = self._update(self._state, batch)
        self._episode_returns = self._episode_returns[-100:]
        self.iteration += 1
        mean_ret = (
            float(np.mean(self._episode_returns)) if self._episode_returns else 0.0
        )
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": mean_ret,
            "num_env_steps_sampled": self._total_steps,
            "env_steps_per_sec": steps / max(time.time() - t0, 1e-9),
            **{k: float(v) for k, v in metrics.items()},
        }

    def save(self, path: Optional[str] = None) -> str:
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        host = jax.tree_util.tree_map(np.asarray, self._state)
        return Checkpoint.from_dict(
            {"learner_state": host, "iteration": self.iteration}
        ).to_directory(path)

    def restore(self, path: str) -> None:
        import jax
        import jax.numpy as jnp

        from ray_tpu.air.checkpoint import Checkpoint

        d = Checkpoint.from_directory(path).to_dict()
        self._state = jax.tree_util.tree_map(jnp.asarray, d["learner_state"])
        self.iteration = d["iteration"]

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners = []
