"""SampleBatch: columnar rollout container.

ray: rllib/policy/sample_batch.py (SampleBatch / concat_samples) — reduced
to a dict of numpy arrays with the standard column names.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
LOGPS = "action_logp"
VALUES = "vf_preds"
ADVANTAGES = "advantages"
RETURNS = "value_targets"


class SampleBatch(dict):
    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch(
            {k: np.concatenate([np.asarray(b[k]) for b in batches]) for k in keys}
        )

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        for i in range(0, n - size + 1, size):
            yield SampleBatch({k: np.asarray(v)[i : i + size] for k, v in self.items()})


def compute_gae(
    rewards: np.ndarray,
    values: np.ndarray,
    dones: np.ndarray,
    bootstrap_value: np.ndarray,
    gamma: float = 0.99,
    lam: float = 0.95,
):
    """Generalized advantage estimation over a [T, N] rollout
    (ray: rllib/evaluation/postprocessing.py compute_gae_for_sample_batch).

    Vectorized across the env axis; the time recursion runs backward in
    numpy on the host — rollout post-processing is not the hot loop, the
    learner's jitted update is."""
    T, N = rewards.shape
    adv = np.zeros((T, N), dtype=np.float32)
    lastgaelam = np.zeros(N, dtype=np.float32)
    for t in reversed(range(T)):
        nextvalue = bootstrap_value if t == T - 1 else values[t + 1]
        nonterminal = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * nextvalue * nonterminal - values[t]
        lastgaelam = delta + gamma * lam * nonterminal * lastgaelam
        adv[t] = lastgaelam
    returns = adv + values
    return adv, returns
