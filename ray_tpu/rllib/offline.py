"""Offline RL: train from logged experience, no environment stepping.

ray: rllib/offline/dataset_reader.py (DatasetReader feeding an algorithm
from a ray.data Dataset of logged transitions) + dataset_writer.py /
json_writer.py (experience logging).  TPU-first shape: experiences are
columnar — a parquet round-trip of {obs, actions, rewards, next_obs,
dones} arrays feeds the learner's jitted scanned updates exactly like a
live replay buffer; there is no per-row Python in the path.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


def write_experiences(batch: Dict[str, np.ndarray], path: str,
                      *, parallelism: int = 4) -> List[str]:
    """Log a batch of transitions to parquet files (ray: dataset/json
    writer output_config).  `batch` columns: obs [N, D] float, actions [N]
    int, rewards [N] float, next_obs [N, D] float, dones [N] float/bool.
    Observation rows are flattened per-component columns so the parquet
    schema stays scalar-typed."""
    import pyarrow as pa

    import ray_tpu.data as rdata

    _n, d = np.asarray(batch["obs"]).shape
    cols: Dict[str, np.ndarray] = {}
    obs = np.asarray(batch["obs"], np.float32)
    nxt = np.asarray(batch["next_obs"], np.float32)
    for j in range(d):
        cols[f"obs_{j}"] = obs[:, j]
        cols[f"next_obs_{j}"] = nxt[:, j]
    cols["actions"] = np.asarray(batch["actions"], np.int64)
    cols["rewards"] = np.asarray(batch["rewards"], np.float32)
    cols["dones"] = np.asarray(batch["dones"], np.float32)
    # Columnar end-to-end: numpy -> Arrow table -> zero-copy table-slice
    # shards -> parquet, no per-row Python objects anywhere.
    ds = rdata.from_arrow(pa.table(cols), parallelism=parallelism)
    return ds.write_parquet(path)


class OfflineData:
    """Reader over logged experiences (ray: offline/dataset_reader.py:
    DatasetReader.next() serving train batches from a data Dataset).

    Accepts parquet paths (as written by write_experiences) or any
    ray_tpu.data Dataset with the same columns.
    """

    def __init__(self, source):
        import ray_tpu.data as rdata

        if isinstance(source, (str, list)):
            self.dataset = rdata.read_parquet(source)
        else:
            self.dataset = source
        self._cols: Optional[Dict[str, np.ndarray]] = None

    def _materialize(self) -> Dict[str, np.ndarray]:
        if self._cols is None:
            batches = list(self.dataset.iter_batches(batch_size=65536))
            if not batches:
                raise ValueError(
                    "offline experience dataset is empty — nothing to train on"
                )
            keys = batches[0].keys()
            merged = {
                k: np.concatenate([np.asarray(b[k]) for b in batches])
                for k in keys
            }
            obs_keys = sorted(
                (k for k in merged if k.startswith("obs_")),
                key=lambda k: int(k.split("_")[1]),
            )
            nxt_keys = sorted(
                (k for k in merged if k.startswith("next_obs_")),
                key=lambda k: int(k.split("_")[2]),
            )
            self._cols = {
                "obs": np.stack([merged[k] for k in obs_keys], axis=1).astype(
                    np.float32
                ),
                "next_obs": np.stack(
                    [merged[k] for k in nxt_keys], axis=1
                ).astype(np.float32),
                "actions": merged["actions"].astype(np.int64),
                "rewards": merged["rewards"].astype(np.float32),
                "dones": merged["dones"].astype(np.float32),
            }
        return self._cols

    @property
    def size(self) -> int:
        return len(self._materialize()["actions"])

    @property
    def obs_size(self) -> int:
        return self._materialize()["obs"].shape[1]

    @property
    def num_actions(self) -> int:
        return int(self._materialize()["actions"].max()) + 1

    def fill_buffer(self, buffer) -> int:
        """Bulk-load into a ReplayBuffer (the offline algorithms sample
        minibatches from it exactly like live replay)."""
        c = self._materialize()
        buffer.add_batch(
            c["obs"], c["actions"], c["rewards"], c["next_obs"], c["dones"]
        )
        return len(c["actions"])

    def iter_batches(self, batch_size: int, *, seed: int = 0,
                     epochs: Optional[int] = 1) -> Iterator[Dict[str, Any]]:
        """Shuffled minibatch iterator (for algorithms that stream rather
        than replay)."""
        c = self._materialize()
        n = len(c["actions"])
        rng = np.random.default_rng(seed)
        e = 0
        while epochs is None or e < epochs:
            order = rng.permutation(n)
            for i in range(0, n - batch_size + 1, batch_size):
                idx = order[i : i + batch_size]
                yield {k: v[idx] for k, v in c.items()}
            e += 1
