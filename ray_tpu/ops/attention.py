"""Attention ops: reference jnp, blockwise (flash-semantics) scan, dispatcher.

No counterpart exists in the reference — it delegates all math to torch
(SURVEY.md §5.7: no attention/sequence-parallel code anywhere in python/ray).
Built TPU-first: the blockwise form keeps the working set in VMEM-sized tiles
and is what the pallas kernel (ops/pallas/flash_attention.py) and ring
attention (ops/ring_attention.py) are built from.

Shapes follow [batch, seq, heads, head_dim] throughout.  GQA is expressed by
n_kv_heads < n_heads; kv heads are repeated on the fly.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv(k: jax.Array, n_heads: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, H, D] by repeating groups (GQA)."""
    n_kv = k.shape[2]
    if n_kv == n_heads:
        return k
    assert n_heads % n_kv == 0
    return jnp.repeat(k, n_heads // n_kv, axis=2)


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset: int = 0,
) -> jax.Array:
    """O(S^2) materialized-scores attention. Ground truth for tests.

    q_offset: absolute position of q[0] relative to k[0] (decode/ring steps).
    """
    b, sq, h, d = q.shape
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    if causal:
        sk = k.shape[1]
        qpos = jnp.arange(sq)[:, None] + q_offset
        kpos = jnp.arange(sk)[None, :]
        logits = jnp.where(qpos >= kpos, logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_size: int = 512,
    q_offset: int = 0,
) -> jax.Array:
    """Flash-attention semantics in pure JAX: scan over KV blocks with an
    online softmax, never materializing the [S, S] score matrix.  XLA keeps
    the per-block compute on the MXU; memory is O(S * block).

    Also the inner step of ring attention, where successive KV blocks arrive
    over ICI (ops/ring_attention.py).
    """
    b, sq, h, d = q.shape
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    sk = k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    if sk % block_size != 0:
        block_size = sk  # fall back to one block rather than pad
    n_blocks = sk // block_size

    qf = (q * scale).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    k_blocks = kf.reshape(b, n_blocks, block_size, h, d).transpose(1, 0, 2, 3, 4)
    v_blocks = vf.reshape(b, n_blocks, block_size, h, d).transpose(1, 0, 2, 3, 4)

    qpos = jnp.arange(sq) + q_offset

    def step(carry, blk):
        acc, m, l = carry
        kb, vb, kpos = blk
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kb)
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
            logits = jnp.where(mask[None, None], logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # Guard: a fully-masked row has logits == m_new == NEG_INF; exp(0)=1
        # would poison l. Force those probabilities to 0.
        p = jnp.where(
            logits <= NEG_INF / 2, 0.0, jnp.exp(logits - m_new[..., None])
        )
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, vb)
        return (acc_new, m_new, l_new), None

    kpos_blocks = (jnp.arange(sk).reshape(n_blocks, block_size))
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(step, (acc0, m0, l0), (k_blocks, v_blocks, kpos_blocks))
    out = acc / jnp.maximum(l[..., None], 1e-37)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_size: int = 512,
    impl: Optional[str] = None,
) -> jax.Array:
    """Dispatching attention entry point used by models/.

    impl: None (auto) | "reference" | "blockwise" | "pallas".
    Auto picks the pallas flash kernel on TPU when shapes are tile-aligned,
    else the blockwise scan.
    """
    if impl is None:
        if _on_tpu() and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0 and q.shape[-1] % 128 == 0:
            impl = "pallas"
        elif q.shape[1] > block_size:
            impl = "blockwise"
        else:
            impl = "reference"
    if impl == "reference":
        return reference_attention(q, k, v, causal=causal, scale=scale)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, causal=causal, scale=scale, block_size=block_size)
    if impl == "pallas":
        from ray_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")
