"""Ring attention: causal attention over a sequence-sharded axis via ICI.

Absent from the reference entirely (SURVEY.md §5.7 — it has no sequence/
context parallelism).  TPU-native design: activations are sharded along a
`seq` mesh axis; KV chunks rotate around the ring with `ppermute` while each
device accumulates online-softmax partials for its local Q chunk.  Compute
(MXU matmuls on the local chunk) overlaps with the next chunk's ICI transfer
under XLA's latency-hiding scheduler.

Used through shard_map; composes with data/fsdp/tensor sharding on the other
mesh axes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import NEG_INF, _repeat_kv


def _partial_attention(q, k, v, q_offset, k_offset, causal, scale):
    """Online-softmax partials (acc, m, l) of q against one KV chunk, f32.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]; offsets are absolute positions of
    element 0 along the global sequence.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qf = (q * scale).astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))
    if causal:
        qpos = q_offset + jnp.arange(sq)[:, None]
        kpos = k_offset + jnp.arange(sk)[None, :]
        logits = jnp.where((qpos >= kpos)[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)
    p = jnp.where(logits <= NEG_INF / 2, 0.0, jnp.exp(logits - m[..., None]))
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return acc, m, l


def _combine(a, b):
    """Merge two online-softmax partial triples."""
    acc_a, m_a, l_a = a
    acc_b, m_b, l_b = b
    m = jnp.maximum(m_a, m_b)
    ca = jnp.exp(m_a - m)
    cb = jnp.exp(m_b - m)
    return acc_a * ca[..., None] + acc_b * cb[..., None], m, l_a * ca + l_b * cb


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Per-shard body (call inside shard_map with seq sharded on axis_name).

    q, k, v: local chunks [B, S_local, H, D]; the global sequence is the
    concatenation over the axis in mesh order.
    """
    h = q.shape[2]
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    chunk = q.shape[1]
    q_offset = my * chunk

    b, sq, _, d = q.shape
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, carry):
        acc, m, l, kc, vc = carry
        src = (my - s) % n
        part = _partial_attention(q, kc, vc, q_offset, src * chunk, causal, scale)
        acc, m, l = _combine((acc, m, l), part)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return acc, m, l, kc, vc

    acc, m, l, _, _ = jax.lax.fori_loop(0, n, step, (acc0, m0, l0, k, v))
    out = acc / jnp.maximum(l[..., None], 1e-37)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    causal: bool = True,
) -> jax.Array:
    """Convenience wrapper: shard_map ring_attention over a mesh.

    Inputs are global [B, S, H, D] arrays; S is sharded over seq_axis, B over
    batch_axes, heads over head_axis.  The caller must ensure S divides the
    seq-axis size (the model dispatcher checks); batch/head specs are
    shape-fitted — a dim that doesn't divide runs replicated, which is
    correct, just unsharded.
    """
    from ray_tpu.parallel.sharding import _fit_spec, shard_map

    def fit(x):
        spec = P(batch_axes, seq_axis, head_axis, None)
        fitted = _fit_spec(x.shape, spec, mesh)
        if fitted[1] != seq_axis:
            raise ValueError(
                f"seq length {x.shape[1]} not divisible by mesh axis "
                f"{seq_axis!r} ({mesh.shape[seq_axis]})"
            )
        return fitted

    qspec, kspec = fit(q), fit(k)
    body = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(qspec, kspec, kspec),
        out_specs=qspec,
        check_vma=False,
    )(q, k, v)
