"""Pallas TPU flash attention: forward + backward kernels.

Forward: online softmax, one (block_q, block_k) tile pair per grid step on a
4-D grid (batch, head, q_tile, kv_tile); accumulator/max/denominator live in
VMEM scratch carried across the innermost kv dimension, so VMEM holds only
the current tiles (full-K/V-resident designs blow the ~16MB/core budget and
a 128-tile grid design starves the MXU at ~3 TFLOP/s on v5e).  The per-row
logsumexp is saved for the backward.

Backward: two pallas kernels with flash-style in-kernel recompute (no [S,S]
materialization, O(S) memory):
  - dq kernel, grid (b, h, q_tile, kv_tile): recompute P from (q, k, lse),
    accumulate dq = scale * sum_kv P*(dP - delta) @ K in scratch.
  - dkv kernel, grid (b, h, kv_tile, q_tile): accumulate dv = P^T @ dO and
    dk = (P*(dP - delta))^T @ q_scaled in scratch.

Causal masking skips fully-masked tile pairs via pl.when predication.

On non-TPU backends the kernels run in interpret mode, so tests on the
virtual CPU mesh exercise the same code path.

Reference parity note: the reference (Ray) has no attention kernels at all
(SURVEY.md §5.7) — this is TPU-native new work.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only helpers; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30
_LANES = 128  # VPU lane count: row-scalar scratch is kept lane-broadcast


def _scratch(shape, dtype):
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    # Generic scratch allocation: works in interpret mode (scratch is
    # allocated there too, so this must be a real scratch spec).
    return jax.ShapeDtypeStruct(shape, dtype)


# -- forward ---------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool,
):
    # Blocks: q/o [1, 1, bq, D]; k/v [1, 1, bk, D]; lse [1, 1, bq, 1].
    # Scratch (carried across the kv grid dim): acc [bq, D] f32,
    # m/l [bq, LANES] f32 (lane-broadcast row scalars).
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    q_start = qi * bq
    k_start = ki * bk

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    run = (k_start <= q_start + bq - 1) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(qpos >= kpos, logits, NEG_INF)
        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_blk = jnp.max(logits, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.where(logits <= NEG_INF / 2, 0.0, jnp.exp(logits - m_new))
        corr = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.maximum(l, 1e-37)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, :1] + jnp.log(l_safe)


def _flash_fwd(q, k, v, *, causal, scale, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # Kernels work in [B, H, S, D].
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (b, h, sq // block_q, sk // block_k)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d), jnp.float32),
            _scratch((block_q, _LANES), jnp.float32),
            _scratch((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse


# -- backward --------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref,
    *, scale: float, causal: bool,
):
    # q/do/dq [1, 1, bq, D]; k/v [1, 1, bk, D]; lse/delta [1, 1, bq, 1].
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    q_start = qi * bq
    k_start = ki * bk

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (k_start <= q_start + bq - 1) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # pre-scaled
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # [bq, 1]
        delta = delta_ref[0, 0]  # [bq, 1]
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(qpos >= kpos, logits, NEG_INF)
        p = jnp.exp(logits - lse)  # masked -> exp(-inf) = 0
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        ds = p * (dp - delta)
        acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, scale: float, causal: bool,
):
    # Grid (b, h, kv_tile, q_tile) — q innermost so k/v blocks stay resident.
    # k/v/dk/dv [1, 1, bk, D]; q/do [1, 1, bq, D]; lse/delta [1, 1, bq, 1].
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    bk = k_ref.shape[2]
    bq = q_ref.shape[2]
    k_start = ki * bk
    q_start = qi * bq

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    run = (q_start + bq - 1 >= k_start) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # [bq, 1]
        delta = delta_ref[0, 0]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(qpos >= kpos, logits, NEG_INF)
        p = jnp.exp(logits - lse)
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        # q is pre-scaled, so this accumulates the true dk.
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, *, causal, scale, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, computed outside.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)[..., None]  # [B, H, Sq, 1]

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[_scratch((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            _scratch((block_k, d), jnp.float32),
            _scratch((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)
    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3),
        dv.transpose(0, 2, 1, 3),
    )


# -- custom_vjp wiring -----------------------------------------------------


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, bwd_block_q, bwd_block_k):
    out, _ = _flash_fwd(
        q, k, v, causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, bwd_block_q, bwd_block_k):
    out, lse = _flash_fwd(
        q, k, v, causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, bwd_block_q, bwd_block_k, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(
        q, k, v, out, lse, g,
        causal=causal, scale=scale, block_q=bwd_block_q, block_k=bwd_block_k,
        interpret=_interpret(),
    )


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    bwd_block_q: int = 1024,
    bwd_block_k: int = 512,
) -> jax.Array:
    """Flash attention, [B, S, H, D] layout, GQA via repeated kv heads.

    Forward tiles default larger than backward: the bwd kernels hold four
    [bq, bk] f32 intermediates (logits/p/dp/ds) at once, so 1024x1024 there
    would exceed the ~16MB VMEM scoped budget."""
    h = q.shape[2]
    if k.shape[2] != h:
        from ray_tpu.ops.attention import _repeat_kv

        k = _repeat_kv(k, h)
        v = _repeat_kv(v, h)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    # Shrink each tile to the largest 128-multiple divisor of its sequence
    # length (tail tiles would be silently dropped by the grid floor
    # division); only truly ragged lengths fall back to the blockwise scan.
    block_q = _fit_block(q.shape[1], block_q)
    block_k = _fit_block(k.shape[1], block_k)
    bwd_block_q = _fit_block(q.shape[1], bwd_block_q)
    bwd_block_k = _fit_block(k.shape[1], bwd_block_k)
    if None in (block_q, block_k, bwd_block_q, bwd_block_k):
        from ray_tpu.ops.attention import blockwise_attention

        return blockwise_attention(q, k, v, causal=causal, scale=scale)
    return _flash(q, k, v, causal, scale, block_q, block_k, bwd_block_q, bwd_block_k)


def _fit_block(s: int, requested: int) -> Optional[int]:
    """Tile size that divides s: the request itself if it divides, else the
    largest 128-multiple <= requested that does; None if neither exists."""
    requested = min(requested, s)
    if s % requested == 0:
        return requested
    for b in range((requested // 128) * 128, 127, -128):
        if s % b == 0:
            return b
    return None
