"""Pallas TPU flash-attention forward kernel.

Forward runs as a pallas kernel (online softmax over KV tiles held in VMEM,
MXU matmuls in f32 accumulation); backward recomputes through the blockwise
JAX implementation (ops/attention.py) under jax.custom_vjp — flash-style
recompute-in-backward, O(S) memory.

On non-TPU backends the kernel runs in interpret mode, so tests on the
virtual CPU mesh exercise the same code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool, block_k: int):
    # Block shapes: q_ref/o_ref [1, 1, bq, D]; k_ref/v_ref [1, 1, Sk, D].
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, D]
    bq = q.shape[0]
    sk = k_ref.shape[2]
    nk = sk // block_k

    q_start = qi * bq

    def body(i, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, block_k]
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            kpos = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            logits = jnp.where(qpos >= kpos, logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.where(logits <= NEG_INF / 2, 0.0, jnp.exp(logits - m_new[:, None]))
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, q_ref.shape[3]), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    if causal:
        # Only blocks with kpos <= last qpos contribute.
        n_iter = jnp.minimum(nk, (q_start + bq + block_k - 1) // block_k)
    else:
        n_iter = nk
    acc, m, l = jax.lax.fori_loop(0, n_iter, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / jnp.maximum(l[:, None], 1e-37)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, causal, scale, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # Kernel works in [B, H, S, D].
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (b, h, sq // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_k=block_k
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    return _flash_fwd(
        q, k, v, causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    return _flash(q, k, v, causal, scale, block_q, block_k), (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, g):
    from ray_tpu.ops.attention import blockwise_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, causal=causal, scale=scale, block_size=block_k
        ),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Flash attention, [B, S, H, D] layout, GQA via repeated kv heads."""
    h = q.shape[2]
    if k.shape[2] != h:
        from ray_tpu.ops.attention import _repeat_kv

        k = _repeat_kv(k, h)
        v = _repeat_kv(v, h)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    if q.shape[1] % block_q or k.shape[1] % block_k:
        # Tail blocks would be silently dropped by the grid/loop floor
        # division; use the blockwise scan (same math) for ragged lengths.
        from ray_tpu.ops.attention import blockwise_attention

        return blockwise_attention(q, k, v, causal=causal, scale=scale)
    return _flash(q, k, v, causal, scale, block_q, block_k)
