"""ray_tpu.ops: TPU compute kernels (no counterpart in the reference, which
delegates all math to torch — SURVEY.md §5.7)."""

from ray_tpu.ops.attention import (
    blockwise_attention,
    dot_product_attention,
    reference_attention,
)
from ray_tpu.ops.ring_attention import ring_attention, ring_attention_sharded

__all__ = [
    "blockwise_attention",
    "dot_product_attention",
    "reference_attention",
    "ring_attention",
    "ring_attention_sharded",
    "flash_attention",
]


def __getattr__(name):
    if name == "flash_attention":
        from ray_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention
    raise AttributeError(name)
