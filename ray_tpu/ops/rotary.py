"""Rotary position embeddings (RoPE). Pure function, fuses into the
surrounding attention projections under XLA."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 10000.0) -> jax.Array:
    """Inverse frequencies for each rotated pair. [head_dim // 2], f32."""
    return 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )


def apply_rope(
    x: jax.Array,
    positions: jax.Array,
    *,
    theta: float = 10000.0,
) -> jax.Array:
    """Rotate [..., seq, heads, head_dim] by absolute positions [seq] (or
    broadcastable [..., seq])."""
    head_dim = x.shape[-1]
    freqs = rope_frequencies(head_dim, theta)
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., S, D/2]
    cos = jnp.cos(angles)[..., :, None, :]
    sin = jnp.sin(angles)[..., :, None, :]
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)
