"""ActorPool: load-balance tasks over a fixed set of actors.

ray: python/ray/util/actor_pool.py — same surface (map / map_unordered /
submit / get_next / get_next_unordered / has_next / push / pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queued if no actor is idle."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future.id] = (self._next_task_index, actor, future)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def get_next(self, timeout=None):
        """Next result in SUBMISSION order.  On timeout the pool state is
        untouched (the slot can be retried); once a result is consumed the
        actor returns to the pool even if the task raised."""
        if self._next_return_index >= self._next_task_index and not self._pending_submits:
            raise StopIteration("no pending results")
        future = self._index_to_future[self._next_return_index]
        ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        _, actor, _ = self._future_to_actor.pop(future.id)
        try:
            return ray_tpu.get(future)
        finally:
            self._return_actor(actor)

    def get_next_unordered(self, timeout=None):
        """Next COMPLETED result, any order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        futures = [f for _, _, f in self._future_to_actor.values()]
        ready, _ = ray_tpu.wait(futures, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        idx, actor, _ = self._future_to_actor.pop(future.id)
        self._index_to_future.pop(idx, None)
        try:
            return ray_tpu.get(future)
        finally:
            self._return_actor(actor)

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor) -> None:
        """Add an idle actor to the pool."""
        self._return_actor(actor)

    def pop_idle(self):
        """Remove and return an idle actor, or None."""
        return self._idle.pop() if self._idle else None
