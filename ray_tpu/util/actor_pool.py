"""ActorPool: load-balance tasks over a fixed set of actors.

Same public surface as ray: python/ray/util/actor_pool.py (map /
map_unordered / submit / get_next / get_next_unordered / has_next / push /
pop_idle), built around a different core: each in-flight call is one
record object, indexed twice — by a monotonically increasing submission
sequence number (for ordered consumption) and by the ObjectRef id (for
completion-order consumption).  Free actors sit in a FIFO deque; work that
arrives while every actor is busy queues in a backlog deque and drains as
records retire.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Any, Callable, Iterable, List

import ray_tpu


class _InFlight:
    __slots__ = ("seq", "actor", "ref")

    def __init__(self, seq, actor, ref):
        self.seq = seq
        self.actor = actor
        self.ref = ref


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = deque(actors)
        self._backlog: deque = deque()  # (fn, value) awaiting a free actor
        self._seq = itertools.count()
        self._by_seq: dict = {}  # seq -> _InFlight
        self._by_ref: dict = {}  # ref.id -> _InFlight

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queued if no actor is idle."""
        if not self._idle:
            self._backlog.append((fn, value))
            return
        actor = self._idle.popleft()
        ref = fn(actor, value)
        rec = _InFlight(next(self._seq), actor, ref)
        self._by_seq[rec.seq] = rec
        self._by_ref[ref.id] = rec

    def has_next(self) -> bool:
        return bool(self._by_seq) or bool(self._backlog)

    def _retire(self, rec: _InFlight) -> None:
        """Drop a consumed record and recycle its actor onto new work."""
        self._by_seq.pop(rec.seq, None)
        self._by_ref.pop(rec.ref.id, None)
        self._idle.append(rec.actor)
        if self._backlog and self._idle:
            self.submit(*self._backlog.popleft())

    def get_next(self, timeout=None):
        """Next result in SUBMISSION order.  On timeout the pool state is
        untouched (the slot can be retried); once a result is consumed the
        actor returns to the pool even if the task raised."""
        if not self._by_seq:
            raise StopIteration("no pending results")
        rec = self._by_seq[min(self._by_seq)]
        ready, _ = ray_tpu.wait([rec.ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        try:
            return ray_tpu.get(rec.ref)
        finally:
            self._retire(rec)

    def get_next_unordered(self, timeout=None):
        """Next COMPLETED result, any order."""
        if not self._by_ref:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(
            [rec.ref for rec in self._by_ref.values()], num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        rec = self._by_ref[ready[0].id]
        try:
            return ray_tpu.get(rec.ref)
        finally:
            self._retire(rec)

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor) -> None:
        """Add an idle actor to the pool."""
        self._idle.append(actor)
        if self._backlog:
            self.submit(*self._backlog.popleft())

    def pop_idle(self):
        """Remove and return an idle actor, or None."""
        return self._idle.pop() if self._idle else None
