"""Queue: an actor-backed distributed FIFO queue.

ray: python/ray/util/queue.py — Queue backed by a single actor, with
blocking put/get via timeouts (the reference uses an asyncio actor; here
the actor is sync with enough concurrency slots that gets don't starve
puts).
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0, max_concurrency: int = 32):
        self.maxsize = maxsize
        self._items: List[Any] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        # Per-side cap on callers parked inside the actor.  The actor has a
        # finite concurrency budget; without the cap, enough getters parked
        # on an empty queue consume every slot and the put that would wake
        # them cannot even enter — a queue that is never actually full/empty
        # stalls for whole chunk windows under fan-in.  Derived from the
        # actor's real max_concurrency (caller-overridable), keeping slack
        # for the non-blocking ops; overflow callers degrade to an immediate
        # try + client-side backoff.
        self._park_budget = max(1, (max_concurrency - 4) // 2)
        self._parked_puts = 0
        self._parked_gets = 0

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return self.maxsize > 0 and len(self._items) >= self.maxsize

    def try_put(self, item: Any) -> bool:
        with self._lock:
            if self.maxsize > 0 and len(self._items) >= self.maxsize:
                return False
            self._items.append(item)
            self._not_empty.notify()
            return True

    def try_put_batch(self, items: List[Any]) -> bool:
        with self._lock:
            if self.maxsize > 0 and len(self._items) + len(items) > self.maxsize:
                return False
            self._items.extend(items)
            self._not_empty.notify_all()
            return True

    def try_get(self) -> tuple:
        with self._lock:
            if not self._items:
                return (False, None)
            item = self._items.pop(0)
            self._not_full.notify()
            return (True, item)

    def blocking_put(self, item: Any, timeout_chunk: float) -> bool:
        """Park inside the actor (one concurrency slot) instead of the
        client polling at ~20 RPC/s — a blocked caller costs ~1 RPC per
        chunk.  Returns whether the item was enqueued this chunk."""
        deadline = time.monotonic() + timeout_chunk
        with self._lock:
            if (
                self._parked_puts >= self._park_budget
                and self.maxsize > 0
                and len(self._items) >= self.maxsize
            ):
                return False  # budget spent: immediate-fail, client backs off
            self._parked_puts += 1
            try:
                while self.maxsize > 0 and len(self._items) >= self.maxsize:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._not_full.wait(remaining)
                self._items.append(item)
                self._not_empty.notify()
                return True
            finally:
                self._parked_puts -= 1

    def blocking_get(self, timeout_chunk: float) -> tuple:
        deadline = time.monotonic() + timeout_chunk
        with self._lock:
            if self._parked_gets >= self._park_budget and not self._items:
                return (False, None)
            self._parked_gets += 1
            try:
                while not self._items:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return (False, None)
                    self._not_empty.wait(remaining)
                item = self._items.pop(0)
                self._not_full.notify()
                return (True, item)
            finally:
                self._parked_gets -= 1

    def try_get_batch(self, n: int) -> tuple:
        with self._lock:
            if len(self._items) < n:
                return (False, None)
            out, self._items = self._items[:n], self._items[n:]
            return (True, out)


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 32)
        # The actor sizes its per-side park budgets off its real concurrency
        # so a caller-supplied max_concurrency cannot reintroduce the
        # park-slot-exhaustion stall.
        self.actor = (
            ray_tpu.remote(_QueueActor)
            .options(**opts)
            .remote(maxsize, max_concurrency=opts["max_concurrency"])
        )

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    _CHUNK = 5.0  # seconds a blocked caller parks actor-side per RPC

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.try_put.remote(item)):
                raise Full
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise Full
            chunk = self._CHUNK if remaining is None else min(remaining, self._CHUNK)
            t0 = time.monotonic()
            if ray_tpu.get(
                self.actor.blocking_put.remote(item, chunk), timeout=chunk + 10
            ):
                return
            if time.monotonic() - t0 < chunk / 2:
                # Park budget saturated: degrade to polling, never past the
                # caller's deadline.
                left = None if deadline is None else deadline - time.monotonic()
                if left is None or left > 0:
                    time.sleep(0.05 if left is None else min(0.05, left))

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.try_get.remote())
            if not ok:
                raise Empty
            return item
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise Empty
            chunk = self._CHUNK if remaining is None else min(remaining, self._CHUNK)
            t0 = time.monotonic()
            ok, item = ray_tpu.get(
                self.actor.blocking_get.remote(chunk), timeout=chunk + 10
            )
            if ok:
                return item
            if time.monotonic() - t0 < chunk / 2:
                left = None if deadline is None else deadline - time.monotonic()
                if left is None or left > 0:
                    time.sleep(0.05 if left is None else min(0.05, left))

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self.actor.try_put_batch.remote(list(items))):
            raise Full

    def get_nowait_batch(self, n: int) -> List[Any]:
        ok, items = ray_tpu.get(self.actor.try_get_batch.remote(n))
        if not ok:
            raise Empty
        return items

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
