"""Distributed tracing: OTel-compatible spans with context in task specs.

ray: python/ray/util/tracing/tracing_helper.py — the reference wraps
remote calls in OpenTelemetry spans and propagates the context INSIDE the
task spec (`_DictPropagator.inject_current_context`, :160), so a task's
execute span parents to its submitter's span across processes.  Same
design here:

  * opt-in (`RAY_TPU_TRACE=1` or `enable_tracing()`), zero overhead off;
  * the ACTIVE trace context lives in a contextvar; submission injects it
    into `spec.trace_ctx` as a W3C-traceparent-style dict, execution
    adopts it, so nested submits chain naturally;
  * spans always record to an in-process buffer that workers flush to the
    head (state API / timeline); when the `opentelemetry` API package is
    importable the same spans ALSO open real OTel spans — with no SDK
    installed those are no-ops, with a user-configured SDK they export
    wherever the user pointed it (the lazy-proxy pattern of the
    reference's _OpenTelemetryProxy:33).
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_enabled = os.environ.get("RAY_TPU_TRACE", "") not in ("", "0")
_current: "contextvars.ContextVar[Optional[Dict[str, str]]]" = contextvars.ContextVar(
    "raytpu_trace_ctx", default=None
)
_buffer: List[Dict[str, Any]] = []
_buffer_lock = threading.Lock()
_MAX_BUFFER = 10000

_otel_tracer = None
_otel_checked = False


def enable_tracing() -> None:
    """Turn span recording on for this process (children inherit via the
    RAY_TPU_TRACE env var when set instead)."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def _otel():
    """Lazy OTel API tracer; None when the package is absent."""
    global _otel_tracer, _otel_checked
    if not _otel_checked:
        _otel_checked = True
        try:
            from opentelemetry import trace as _t

            _otel_tracer = _t.get_tracer("ray_tpu")
        except Exception:
            _otel_tracer = None
    return _otel_tracer


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@contextmanager
def span(name: str, parent: Optional[Dict[str, str]] = None,
         attrs: Optional[Dict[str, Any]] = None):
    """Record one span.  `parent` (e.g. a spec's trace_ctx) wins over the
    ambient context; the new span becomes ambient for the duration, so
    anything submitted inside parents to it."""
    if not _enabled:
        yield None
        return
    up = parent if parent is not None else _current.get()
    ctx = {
        "trace_id": (up or {}).get("trace_id") or _new_id(16),
        "span_id": _new_id(8),
    }
    rec = {
        "name": name,
        "trace_id": ctx["trace_id"],
        "span_id": ctx["span_id"],
        "parent_span_id": (up or {}).get("span_id"),
        "start": time.time(),
        "attrs": dict(attrs or {}),
        "pid": os.getpid(),
    }
    token = _current.set(ctx)
    otel = _otel()
    om = otel.start_as_current_span(name) if otel is not None else None
    if om is not None:
        om.__enter__()
    try:
        yield ctx
    finally:
        if om is not None:
            try:
                om.__exit__(None, None, None)
            except Exception:
                pass
        _current.reset(token)
        rec["end"] = time.time()
        with _buffer_lock:
            _buffer.append(rec)
            while len(_buffer) > _MAX_BUFFER:
                _buffer.pop(0)
        # Completed spans also land in the process's flight-recorder ring
        # (telemetry.py): a crash dump shows what this process was doing
        # in its last seconds, span by span.
        try:
            from ray_tpu._private import telemetry as _telemetry

            _telemetry.note(
                "span",
                name=rec["name"],
                span_id=rec["span_id"],
                dur_ms=round((rec["end"] - rec["start"]) * 1000, 3),
            )
        except Exception:
            pass


def record_span(
    name: str,
    start: float,
    end: float,
    parent: Optional[Dict[str, str]] = None,
    attrs: Optional[Dict[str, Any]] = None,
    ctx: Optional[Dict[str, str]] = None,
) -> Optional[Dict[str, str]]:
    """Record an ALREADY-FINISHED span with explicit epoch timestamps.

    For intervals whose boundaries are only known after the fact — e.g.
    the "detect" stage of an elastic re-mesh starts on the head before the
    driver notices, and "resume" ends inside a report callback.  `ctx`
    pins the span's own ids so sibling spans recorded earlier can already
    have parented to it; returns the span's context for further chaining.
    """
    if not _enabled:
        return None
    c = {
        "trace_id": (ctx or parent or {}).get("trace_id") or _new_id(16),
        "span_id": (ctx or {}).get("span_id") or _new_id(8),
    }
    rec = {
        "name": name,
        "trace_id": c["trace_id"],
        "span_id": c["span_id"],
        "parent_span_id": (parent or {}).get("span_id"),
        "start": start,
        "end": end,
        "attrs": dict(attrs or {}),
        "pid": os.getpid(),
    }
    with _buffer_lock:
        _buffer.append(rec)
        while len(_buffer) > _MAX_BUFFER:
            _buffer.pop(0)
    return c


def drain_spans() -> List[Dict[str, Any]]:
    """Take the buffered spans (worker flush loops ship them to the head)."""
    with _buffer_lock:
        out, _buffer[:] = _buffer[:], []
    return out


def apply_clock_offset(
    spans: List[Dict[str, Any]], offset_s: float
) -> List[Dict[str, Any]]:
    """Land one process's span timestamps on the receiver's clock.  The
    head calls this at span ingest with its handshake-estimated per-conn
    offset; offset 0 returns the input unchanged (no copy)."""
    if not offset_s:
        return spans
    out = []
    for s in spans:
        c = dict(s)
        if isinstance(c.get("start"), (int, float)):
            c["start"] = c["start"] + offset_s
        if isinstance(c.get("end"), (int, float)):
            c["end"] = c["end"] + offset_s
        out.append(c)
    return out


def merge_process_spans(
    streams: List[tuple],
) -> List[Dict[str, Any]]:
    """Merge per-process span streams into ONE ordered timeline.

    `streams` is [(clock_offset_s, spans), ...] — each process's spans
    with the offset that lands its clock on the merger's.  Deterministic:
    the result is sorted by corrected start time with span_id as the
    tiebreak, so the same inputs always produce the same order (the
    clock-skew merge test asserts this).  This is the pure core of the
    head's merged `ray_tpu timeline`; the head applies offsets at ingest
    and the timeline export is already merged."""
    out: List[Dict[str, Any]] = []
    for offset_s, spans in streams:
        out.extend(apply_clock_offset(list(spans), offset_s))
    out.sort(key=lambda s: (s.get("start", 0.0), s.get("span_id") or ""))
    return out


def window_chrome_events(
    events: List[Dict[str, Any]],
    last: Optional[float] = None,
    since: Optional[float] = None,
    now: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Bound a chrome-trace event list to a time window (pure core of
    `ray_tpu timeline --last SECONDS / --since TS`).

    `last` = keep events whose END falls within the trailing window of
    that many seconds; `since` = keep events ending at/after that epoch
    timestamp (seconds).  `since` wins when both are given; neither
    returns the input unchanged.  Events carry `ts` (µs) and optionally
    `dur` (µs) — an event straddling the cutoff is KEPT (its tail is in
    the window; truncating would misrepresent a long-running span)."""
    if since is None and not last:
        return events
    now = time.time() if now is None else now
    cutoff_us = (since if since is not None else now - float(last)) * 1e6
    out = []
    for e in events:
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            out.append(e)  # malformed/clockless rows stay visible
            continue
        if ts + (e.get("dur") or 0) >= cutoff_us:
            out.append(e)
    return out


def spans_to_chrome_trace(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Chrome-trace 'X' events for `ray_tpu timeline`-style viewing."""
    return [
        {
            "name": s["name"],
            "ph": "X",
            "ts": int(s["start"] * 1e6),
            "dur": int(max(s.get("end", s["start"]) - s["start"], 0) * 1e6),
            "pid": s.get("pid", 0),
            "tid": 0,
            "args": {
                "trace_id": s["trace_id"],
                "span_id": s["span_id"],
                "parent_span_id": s.get("parent_span_id"),
                **s.get("attrs", {}),
            },
        }
        for s in spans
    ]
