from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import placement_group, remove_placement_group, PlacementGroup
from ray_tpu.util.queue import Empty, Full, Queue
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "ActorPool",
    "Empty",
    "Full",
    "Queue",
    "placement_group",
    "remove_placement_group",
    "PlacementGroup",
    "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]
