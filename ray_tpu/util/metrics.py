"""User-facing metric API: Counter / Gauge / Histogram.

ray: python/ray/util/metrics.py (backed there by OpenCensus through the
Cython layer, src/ray/stats/metric.h:103).  Here metrics record in-process
into a registry; `collect()` snapshots every metric of the current process
(driver or worker) — a scrape endpoint can export them.  Tag semantics
match the reference: default_tags at construction, per-record overrides.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: Dict[str, "Metric"] = {}


def _tag_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Tuple[str, ...] = ()):
        if not name:
            raise ValueError("metric name must be non-empty")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _REGISTRY_LOCK:
            existing = _REGISTRY.get(name)
            if existing is not None and type(existing) is not type(self):
                raise ValueError(
                    f"metric {name!r} already registered as {type(existing).__name__}"
                )
            _REGISTRY[name] = self

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        unknown = set(tags) - set(self.tag_keys)
        if unknown:
            raise ValueError(f"tags {unknown} not in declared tag_keys {self.tag_keys}")
        self._default_tags = dict(tags)
        return self

    def _resolve_tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        if tags:
            unknown = set(tags) - set(self.tag_keys)
            if unknown:
                raise ValueError(
                    f"tags {unknown} not in declared tag_keys {self.tag_keys}"
                )
            merged.update(tags)
        return merged


class Counter(Metric):
    """Monotonic counter (ray: util/metrics.py Counter)."""

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc() requires value >= 0")
        k = _tag_key(self._resolve_tags(tags))
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def snapshot(self) -> Dict[Tuple, float]:
        with self._lock:
            return dict(self._values)


class Gauge(Metric):
    """Last-value gauge (ray: util/metrics.py Gauge)."""

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = _tag_key(self._resolve_tags(tags))
        with self._lock:
            self._values[k] = float(value)

    def snapshot(self) -> Dict[Tuple, float]:
        with self._lock:
            return dict(self._values)


class Histogram(Metric):
    """Bucketed histogram (ray: util/metrics.py Histogram)."""

    def __init__(self, name, description="", boundaries: Optional[List[float]] = None, tag_keys=()):
        super().__init__(name, description, tag_keys)
        if not boundaries:
            raise ValueError("Histogram requires bucket boundaries")
        self.boundaries = sorted(boundaries)
        self._buckets: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        self.observe_resolved(_tag_key(self._resolve_tags(tags)), value)

    def resolved_key(self, tags: Optional[Dict[str, str]] = None) -> Tuple:
        """Pre-resolve a tag set into the internal series key.  Hot paths
        observing the SAME tags repeatedly (the head folds ~8 stage
        samples per finished task) cache this once instead of paying the
        merge + sort per observation."""
        return _tag_key(self._resolve_tags(tags))

    def observe_resolved(self, k: Tuple, value: float):
        with self._lock:
            buckets = self._buckets.setdefault(k, [0] * (len(self.boundaries) + 1))
            idx = 0
            while idx < len(self.boundaries) and value > self.boundaries[idx]:
                idx += 1
            buckets[idx] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def snapshot(self) -> Dict[Tuple, Dict]:
        with self._lock:
            return {
                k: {
                    "buckets": list(v),
                    "sum": self._sums.get(k, 0.0),
                    "count": self._counts.get(k, 0),
                }
                for k, v in self._buckets.items()
            }


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return out if not out[:1].isdigit() else "_" + out


def _prom_escape(v: str) -> str:
    """Label-value escaping per the exposition format: one bad series
    would otherwise make Prometheus reject the whole scrape body."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _prom_labels(tag_key: Tuple, extra: str = "") -> str:
    parts = [f'{k}="{_prom_escape(v)}"' for k, v in tag_key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _prom_histogram_lines(
    pname: str, tag_key: Tuple, boundaries: List[float], d: Dict
) -> List[str]:
    """Exposition lines for one histogram series.  Shared by the local
    registry renderer below and the cluster renderer in
    _private/telemetry.py (pushed per-process snapshots carry their
    boundaries, so the head can render histograms it never constructed).

    The le label is pre-built OUTSIDE the f-string expression: an escape
    inside an f-string expression part is a SyntaxError before Python
    3.12, and this module failing to IMPORT took the whole metric API
    down with it (the standing tier-1 collection error this fixes)."""
    lines: List[str] = []
    cum = 0
    for bound, n in zip(boundaries, d["buckets"]):
        cum += n
        labels = _prom_labels(tag_key, 'le="%s"' % bound)
        lines.append(f"{pname}_bucket{labels} {cum}")
    cum += d["buckets"][-1]
    labels = _prom_labels(tag_key, 'le="+Inf"')
    lines.append(f"{pname}_bucket{labels} {cum}")
    lines.append(f"{pname}_sum{_prom_labels(tag_key)} {d['sum']}")
    lines.append(f"{pname}_count{_prom_labels(tag_key)} {d['count']}")
    return lines


def prometheus_text(extra_gauges: Optional[Dict[str, float]] = None) -> str:
    """Render every registered metric in the Prometheus text exposition
    format (ray: _private/metrics_agent.py:375 re-exports OpenCensus views
    through prometheus_exporter; here the registry renders itself — no
    agent process needed on a single-controller runtime).

    extra_gauges: runtime-level numbers (task counts, store bytes, ...)
    exported alongside the user metrics as plain gauges.
    """
    with _REGISTRY_LOCK:
        metrics = dict(_REGISTRY)
    lines: List[str] = []
    for name, m in sorted(metrics.items()):
        pname = _prom_name(name)
        if isinstance(m, Counter):
            lines.append(f"# HELP {pname}_total {_prom_help(m.description)}")
            lines.append(f"# TYPE {pname}_total counter")
            for k, v in sorted(m.snapshot().items()):
                lines.append(f"{pname}_total{_prom_labels(k)} {v}")
        elif isinstance(m, Gauge):
            lines.append(f"# HELP {pname} {_prom_help(m.description)}")
            lines.append(f"# TYPE {pname} gauge")
            for k, v in sorted(m.snapshot().items()):
                lines.append(f"{pname}{_prom_labels(k)} {v}")
        elif isinstance(m, Histogram):
            lines.append(f"# HELP {pname} {_prom_help(m.description)}")
            lines.append(f"# TYPE {pname} histogram")
            for k, d in sorted(m.snapshot().items()):
                lines.extend(
                    _prom_histogram_lines(pname, k, m.boundaries, d)
                )
    for name, value in sorted((extra_gauges or {}).items()):
        pname = _prom_name(f"ray_tpu_{name}")
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {value}")
    return "\n".join(lines) + "\n"


def collect() -> Dict[str, Dict]:
    """Snapshot every registered metric in this process.  Histograms carry
    their bucket boundaries so a snapshot shipped to another process (the
    telemetry push) renders and aggregates without the Metric object."""
    with _REGISTRY_LOCK:
        metrics = dict(_REGISTRY)
    out: Dict[str, Dict] = {}
    for name, m in metrics.items():
        rec = {
            "type": type(m).__name__,
            "description": m.description,
            "data": m.snapshot() if hasattr(m, "snapshot") else {},
        }
        if isinstance(m, Histogram):
            rec["boundaries"] = list(m.boundaries)
        out[name] = rec
    return out
