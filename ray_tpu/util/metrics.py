"""User-facing metric API: Counter / Gauge / Histogram.

ray: python/ray/util/metrics.py (backed there by OpenCensus through the
Cython layer, src/ray/stats/metric.h:103).  Here metrics record in-process
into a registry; `collect()` snapshots every metric of the current process
(driver or worker) — a scrape endpoint can export them.  Tag semantics
match the reference: default_tags at construction, per-record overrides.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: Dict[str, "Metric"] = {}


def _tag_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Tuple[str, ...] = ()):
        if not name:
            raise ValueError("metric name must be non-empty")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _REGISTRY_LOCK:
            existing = _REGISTRY.get(name)
            if existing is not None and type(existing) is not type(self):
                raise ValueError(
                    f"metric {name!r} already registered as {type(existing).__name__}"
                )
            _REGISTRY[name] = self

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        unknown = set(tags) - set(self.tag_keys)
        if unknown:
            raise ValueError(f"tags {unknown} not in declared tag_keys {self.tag_keys}")
        self._default_tags = dict(tags)
        return self

    def _resolve_tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        if tags:
            unknown = set(tags) - set(self.tag_keys)
            if unknown:
                raise ValueError(
                    f"tags {unknown} not in declared tag_keys {self.tag_keys}"
                )
            merged.update(tags)
        return merged


class Counter(Metric):
    """Monotonic counter (ray: util/metrics.py Counter)."""

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc() requires value >= 0")
        k = _tag_key(self._resolve_tags(tags))
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def snapshot(self) -> Dict[Tuple, float]:
        with self._lock:
            return dict(self._values)


class Gauge(Metric):
    """Last-value gauge (ray: util/metrics.py Gauge)."""

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = _tag_key(self._resolve_tags(tags))
        with self._lock:
            self._values[k] = float(value)

    def snapshot(self) -> Dict[Tuple, float]:
        with self._lock:
            return dict(self._values)


class Histogram(Metric):
    """Bucketed histogram (ray: util/metrics.py Histogram)."""

    def __init__(self, name, description="", boundaries: Optional[List[float]] = None, tag_keys=()):
        super().__init__(name, description, tag_keys)
        if not boundaries:
            raise ValueError("Histogram requires bucket boundaries")
        self.boundaries = sorted(boundaries)
        self._buckets: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = _tag_key(self._resolve_tags(tags))
        with self._lock:
            buckets = self._buckets.setdefault(k, [0] * (len(self.boundaries) + 1))
            idx = 0
            while idx < len(self.boundaries) and value > self.boundaries[idx]:
                idx += 1
            buckets[idx] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def snapshot(self) -> Dict[Tuple, Dict]:
        with self._lock:
            return {
                k: {
                    "buckets": list(v),
                    "sum": self._sums.get(k, 0.0),
                    "count": self._counts.get(k, 0),
                }
                for k, v in self._buckets.items()
            }


def collect() -> Dict[str, Dict]:
    """Snapshot every registered metric in this process."""
    with _REGISTRY_LOCK:
        metrics = dict(_REGISTRY)
    return {
        name: {
            "type": type(m).__name__,
            "description": m.description,
            "data": m.snapshot() if hasattr(m, "snapshot") else {},
        }
        for name, m in metrics.items()
    }
