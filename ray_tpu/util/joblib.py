"""joblib backend: scikit-learn's `n_jobs=-1` parallelism on the cluster.

ray: python/ray/util/joblib/ — register_ray() + a joblib ParallelBackend
that turns every joblib batch (GridSearchCV fits, cross_val_score folds,
bagging members) into runtime tasks.  Usage:

    from ray_tpu.util.joblib import register_ray
    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        GridSearchCV(...).fit(X, y)
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

import ray_tpu
from joblib._parallel_backends import ParallelBackendBase


@ray_tpu.remote
def _run_batch(payload):
    """The one exported trampoline: joblib hands zero-arg BatchedCalls
    callables; a module-level remote fn exports ONCE per session instead
    of re-pickling an identical closure per batch."""
    return payload()


class _Future:
    """concurrent.futures-shaped handle over an ObjectRef (what joblib's
    retrieve path expects back from submit)."""

    def __init__(self, ref):
        self.ref = ref
        self._done = threading.Event()
        self._result: List[Any] = []
        self._error: List[BaseException] = []

    def _complete(self) -> None:
        if not self._done.is_set():
            try:
                self._result.append(ray_tpu.get(self.ref, timeout=0))
            except BaseException as e:  # noqa: BLE001 — joblib re-raises
                self._error.append(e)
            self._done.set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.is_set():
            done, _ = ray_tpu.wait([self.ref], num_returns=1, timeout=timeout)
            if not done:
                # NOT latched: the task may still finish — a later result()
                # must return the value, not replay a stale timeout.
                raise TimeoutError(
                    f"result not ready within {timeout}s"
                )  # concurrent.futures contract
            self._complete()
        if self._error:
            raise self._error[0]
        return self._result[0]

    get = result  # legacy AsyncResult surface


class RayTpuBackend(ParallelBackendBase):
    """joblib backend over the task runtime.

    Implements the MODERN submit/retrieve_result_callback contract
    (apply_async is deprecated in joblib 1.5): a single watcher thread
    waits on outstanding refs and fires joblib's completion callbacks as
    tasks ACTUALLY finish, so dispatch of later batches never stalls
    behind an in-order straggler.
    """

    supports_retrieve_callback = True
    supports_inner_max_num_threads = False
    uses_threads = False
    supports_sharedmem = False

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._lock = threading.Lock()
        self._watching: dict = {}  # ref -> (_Future, callback)
        self._wake = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._stopped = False

    # -- lifecycle --------------------------------------------------------
    def configure(self, n_jobs: int = 1, parallel=None, **_kw) -> int:
        ray_tpu.init(ignore_reinit_error=True)
        self.parallel = parallel
        self._n_jobs = self.effective_n_jobs(n_jobs)
        return self._n_jobs

    def effective_n_jobs(self, n_jobs: int) -> int:
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        ray_tpu.init(ignore_reinit_error=True)
        total = int(ray_tpu.cluster_resources().get("CPU", 1))
        if n_jobs is None:
            return total
        if n_jobs < 0:
            # joblib convention: -1 = all, -2 = all but one, ...
            return max(1, total + 1 + n_jobs)
        return min(n_jobs, total)

    # -- submission -------------------------------------------------------
    def submit(self, func: Callable[[], Any], callback=None):
        ref = _run_batch.remote(func)
        fut = _Future(ref)
        # A terminated backend can be reused (joblib documents reusing a
        # Parallel object): retire any stopping watcher FIRST — outside the
        # lock, since the watcher takes it per iteration and a locked join
        # would deadlock — then register under a fresh one.
        with self._lock:
            old = self._watcher
            need_restart = (
                self._stopped or old is None or not old.is_alive()
            )
        if need_restart:
            if old is not None and old.is_alive():
                self._stopped = True
                self._wake.set()
            if old is not None:
                old.join(timeout=5)
            with self._lock:
                if self._stopped or self._watcher is None or not self._watcher.is_alive():
                    self._stopped = False
                    self._watcher = threading.Thread(
                        target=self._watch_loop, daemon=True, name="joblib-raytpu"
                    )
                    self._watcher.start()
        with self._lock:
            self._watching[ref] = (fut, callback)
        self._wake.set()
        return fut

    def retrieve_result_callback(self, future: _Future):
        return future.result()

    def _watch_loop(self) -> None:
        while not self._stopped:
            with self._lock:
                refs = list(self._watching)
            if not refs:
                self._wake.wait(timeout=0.5)
                self._wake.clear()
                continue
            try:
                done, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.2)
            except Exception as e:  # runtime shut down mid-Parallel
                # Fail every outstanding future so joblib surfaces the
                # error instead of hanging on callbacks that never fire.
                with self._lock:
                    entries = list(self._watching.values())
                    self._watching.clear()
                for fut, callback in entries:
                    fut._error.append(e)
                    fut._done.set()
                    if callback is not None:
                        try:
                            callback(fut)
                        except Exception:
                            pass
                return
            for ref in done:
                with self._lock:
                    entry = self._watching.pop(ref, None)
                if entry is None:
                    continue
                fut, callback = entry
                fut._complete()
                if callback is not None:
                    try:
                        callback(fut)
                    except Exception:
                        pass  # joblib's callback errors are its own affair

    def abort_everything(self, ensure_ready: bool = True) -> None:
        # A failed fold aborts the Parallel call: cancel what's still
        # running so the cluster doesn't burn CPU on doomed batches.
        with self._lock:
            pending = list(self._watching)
            self._watching.clear()
        for ref in pending:
            try:
                ray_tpu.cancel(ref)
            except Exception:
                pass
        if ensure_ready:
            self.configure(n_jobs=self._n_jobs, parallel=self.parallel)

    def terminate(self) -> None:
        self._stopped = True
        self._wake.set()


def register_ray() -> None:
    """Register the 'ray_tpu' joblib backend (ray: util/joblib register_ray)."""
    from joblib import register_parallel_backend

    register_parallel_backend("ray_tpu", RayTpuBackend)
