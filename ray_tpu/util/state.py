"""State API: live introspection of tasks/actors/objects/nodes/workers.

ray: python/ray/experimental/state/api.py (`ray list tasks/actors/objects`,
summarize) + dashboard/state_aggregator.py.  Driver-side reads straight
from the runtime's tables; the bounded task-event sink
(runtime.task_events, analogue of gcs_task_manager.h ring buffer) supplies
finished-task history.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def _rt():
    from ray_tpu._private.runtime import get_runtime

    return get_runtime()


def _attached_request(verb: str, kwargs: Optional[Dict[str, Any]] = None):
    """Route a state verb through the head's `state_list` request op when
    this process is an attached client (worker / --address driver), so
    every list_* answer matches what the head itself would say.  Returns
    (result, True) when routed, (None, False) when head-local."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    if wr is None:
        return None, False
    return wr.request("state_list", (verb, kwargs or {})), True


def list_tasks(*, include_finished: bool = True, limit: int = 1000) -> List[Dict[str, Any]]:
    """Live tasks (PENDING/READY/RUNNING) + bounded finished history."""
    out, routed = _attached_request(
        "tasks", {"include_finished": include_finished, "limit": limit}
    )
    if routed:
        return out
    rt = _rt()
    out: List[Dict[str, Any]] = []
    with rt.lock:
        for tid, rec in rt.tasks.items():
            out.append(
                {
                    "task_id": tid,
                    "name": rec.spec.name,
                    "state": rec.state,
                    "node_id": rec.node_id,
                    "worker_id": rec.worker_id,
                    "actor_id": rec.spec.actor_id,
                    "parent_task_id": rec.spec.parent_task_id,
                    "attempt": rec.spec.attempt,
                }
            )
        # Lease-dispatched tasks the head never scheduled (caller-reported
        # RUNNING via batched task events — ray: gcs_task_manager.h:61).
        out.extend(dict(e) for e in rt.direct_running.values())
        if include_finished:
            out.extend(dict(e) for e in rt.task_events)
    return out[:limit]


def list_spans(limit: int = 1000) -> List[Dict[str, Any]]:
    """Trace spans (util/tracing.py): worker spans arrive via the batched
    flush; the driver/head process's own buffer is folded in here."""
    out, routed = _attached_request("spans", {"limit": limit})
    if routed:
        return out
    from ray_tpu.util import tracing

    rt = _rt()
    local = tracing.drain_spans()
    with rt.lock:
        rt.trace_spans.extend(local)
        return list(rt.trace_spans)[-limit:]


def list_actors(limit: int = 1000) -> List[Dict[str, Any]]:
    res, routed = _attached_request("actors", {"limit": limit})
    if routed:
        return res
    rt = _rt()
    out = []
    with rt.lock:
        for aid, info in rt.state.actors.items():
            out.append(
                {
                    "actor_id": aid,
                    "name": info.name,
                    "state": info.state,
                    "node_id": info.node_id,
                    "worker_id": info.worker_id,
                    "num_restarts": info.num_restarts,
                    "namespace": info.namespace,
                    "death_cause": info.death_cause,
                }
            )
    return out[:limit]


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    """Owner-store view: every live object with location + refcount."""
    res, routed = _attached_request("objects", {"limit": limit})
    if routed:
        return res
    rt = _rt()
    store = rt.store
    out = []
    with store._lock:
        for oid in set(store._mem) | set(store._in_shm) | set(store._spilled):
            if oid in store._mem:
                loc, size = "memory", store._mem[oid].size
            elif oid in store._in_shm:
                loc, size = "shm", store._in_shm[oid]
            else:
                loc, size = "spilled", None
            out.append(
                {
                    "object_id": oid,
                    "location": loc,
                    "size_bytes": size,
                    "refcount": store._refcount.get(oid, 0),
                    "ready": store._ready.get(oid, False),
                }
            )
    return out[:limit]


def list_nodes() -> List[Dict[str, Any]]:
    res, routed = _attached_request("nodes")
    if routed:
        return res
    rt = _rt()
    with rt.lock, rt.state.lock:
        lease_counts: Dict[str, int] = {}
        for leases in rt.task_leases.values():
            for le in leases:
                lease_counts[le.node_id] = lease_counts.get(le.node_id, 0) + 1
        store_bytes: Dict[str, int] = {}
        for oid, locs in rt.object_locations.items():
            sz = rt.object_sizes.get(oid, 0)
            for nid in locs:
                store_bytes[nid] = store_bytes.get(nid, 0) + sz
        out = []
        for n in rt.state.nodes.values():
            lc = rt.node_lifecycle.get(n.node_id)
            # Lifecycle is only journaled for autoscaler-managed / drained
            # nodes; statically-launched nodes read as plain ACTIVE.
            state = (lc or {}).get("state") or ("ACTIVE" if n.alive else "DEAD")
            if n.alive and n.draining:
                state = "DRAINING"
            out.append(
                {
                    "node_id": n.node_id,
                    "alive": n.alive,
                    "is_head": n.is_head,
                    "state": state,
                    "resources": dict(n.resources),
                    "available": dict(n.available),
                    "labels": dict(n.labels),
                    "has_daemon": n.node_id in rt.node_daemons,
                    "daemon_pid": rt.node_daemon_pids.get(n.node_id),
                    "lease_count": lease_counts.get(n.node_id, 0),
                    "store_bytes": store_bytes.get(
                        n.node_id, rt.store.shm_usage() if n.is_head else 0
                    ),
                }
            )
        return out


def demand_summary() -> Dict[str, Any]:
    """The head's resource-demand summary (what the elastic autoscaler
    reconciles against): unplaceable SchedulingKey buckets with wait ages,
    pending/RESHAPING placement-group bundles, and serve replica targets
    published by the serve controller."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    if wr is not None:
        return wr.request("demand_summary", None)
    return _rt().demand_summary()


def list_workers() -> List[Dict[str, Any]]:
    res, routed = _attached_request("workers")
    if routed:
        return res
    rt = _rt()
    with rt.lock:
        return [
            {
                "worker_id": wid,
                "node_id": h.node_id,
                "state": h.state,
                "pid": h.pid,
                "actor_id": h.actor_id,
                "current_task": h.current_task,
            }
            for wid, h in rt.workers.items()
        ]


def list_placement_groups() -> List[Dict[str, Any]]:
    res, routed = _attached_request("placement_groups")
    if routed:
        return res
    rt = _rt()
    with rt.state.lock:
        return [
            {
                "placement_group_id": pid,
                "state": pg.state,
                "strategy": pg.strategy,
                "bundles": list(pg.bundles),
                "bundle_nodes": dict(pg.bundle_nodes),
            }
            for pid, pg in rt.state.placement_groups.items()
        ]


def summarize_tasks() -> Dict[str, int]:
    """Count by state (ray: `ray summary tasks`)."""
    res, routed = _attached_request("summarize_tasks")
    if routed:
        return res
    counts: Dict[str, int] = {}
    for t in list_tasks():
        counts[t["state"]] = counts.get(t["state"], 0) + 1
    return counts


def cluster_metrics() -> Dict[str, float]:
    """Runtime counters + store gauges (ray: src/ray/stats/metric_defs.cc
    reduced to the load-bearing set)."""
    res, routed = _attached_request("cluster_metrics")
    if routed:
        return res
    rt = _rt()
    with rt.lock:
        m = dict(rt.metrics)
    m.update(
        {
            "object_store_bytes_used": float(rt.store.shm_usage()),
            "object_store_capacity_bytes": float(rt.store.capacity),
            "objects_spilled": float(len(rt.store._spilled)),
            "live_tasks": float(len(rt.tasks)),
            "live_workers": float(
                sum(1 for h in rt.workers.values() if h.state != "dead")
            ),
            "lineage_entries": float(len(rt.lineage)),
            "lineage_bytes": float(rt.lineage_bytes),
        }
    )
    from ray_tpu._private import wire as _wire

    if _wire.stats_enabled():
        # Control-plane coalescing counters (RAY_TPU_WIRE_STATS=1): the
        # head's own process counters plus every worker/driver snapshot
        # reported over the wire_stats channel.  writes-per-frame below 1.0
        # is the batching win the dashboard/bench read off directly.
        head = _wire.stats()
        with rt.lock:
            remotes = list(rt.worker_wire_stats.values())
        for key in head:
            m[f"wire_{key}"] = float(
                head[key] + sum(s.get(key, 0) for s in remotes)
            )
        m["wire_head_physical_writes"] = float(head["physical_writes"])
        m["wire_head_logical_frames"] = float(head["logical_frames"])
    return m


def list_cluster_events(
    limit: int = 100, severity: str = None, source: str = None
) -> List[Dict[str, Any]]:
    """Structured control-plane events — node/worker/actor transitions with
    severity + source (ray: `ray list cluster-events` over the event files,
    src/ray/util/event.h:102)."""
    out, routed = _attached_request(
        "cluster_events",
        {"limit": limit, "severity": severity, "source": source},
    )
    if routed:
        return out
    return _rt().events.recent(limit=limit, severity=severity, source=source)


def telemetry_summary() -> Dict[str, Any]:
    """The pushed-metrics plane: per-process snapshot ages, the cluster
    aggregate (counters/buckets summed across processes), and the summed
    internal gauges (queue depths, journal counters, wire totals).
    Workers/daemons/drivers push on RAY_TPU_METRICS_PUSH_MS; the head
    folds its own registry in on the same tick (telemetry.py)."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    if wr is not None:
        return wr.request("telemetry", None)
    rt = _rt()
    # Fold a fresh head snapshot in first: a CLI/driver read right after a
    # local metric record must see it without waiting out the tick.
    rt.telemetry.ingest("head", rt.head_telemetry_snapshot())
    return rt.telemetry.summary()


def telemetry_series(name: Optional[str] = None) -> Dict[str, List]:
    """Bounded time series of the cluster aggregate, one ring per metric
    (the GcsTaskManager ring-storage idiom applied to metrics): [(t,
    value), ...] per name, RAY_TPU_TELEMETRY_RING_SAMPLES samples deep."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    if wr is not None:
        return wr.request("telemetry_series", name)
    return _rt().telemetry.series_snapshot(name)


def memory_summary(
    group_by: Optional[str] = None,
    top: int = 20,
    include_events: bool = False,
) -> Dict[str, Any]:
    """Cluster memory introspection: the head's object ledger — per-node
    store/spilled bytes, top-N objects by size, holder attribution (which
    node/pid pins which bytes), leak suspects, and optional group-by
    node|owner|callsite (callsites require RAY_TPU_REF_CALLSITE=1 in the
    creating processes).  `ray_tpu memory` and /api/memory are thin
    wrappers over this (ray: `ray memory` over the ReferenceCounter
    tables, SURVEY §2.1)."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    payload = {
        "group_by": group_by,
        "top": top,
        "include_events": include_events,
    }
    if wr is not None:
        return wr.request("memory_summary", payload)
    return _rt().memory_summary(**payload)


def task_summary(slow: int = 10) -> Dict[str, Any]:
    """Per-task lifecycle attribution (`ray_tpu tasks`): stage-duration
    stats (p50/p95/p99 per stage), the accounted-vs-wall fraction, and
    the N slowest tasks with their stage breakdowns + critical stage —
    plus currently-live tasks with the stage each is stuck in.  The fold
    runs over the head's finished-task ring (runtime.task_events, the
    gcs_task_manager ring analogue) upgraded into a per-task state
    machine (telemetry.STAGE_ORDER)."""
    out, routed = _attached_request("task_summary", {"slow": slow})
    if routed:
        return out
    return _rt().task_summary_local(slow=slow)


def profile_start(hz: Optional[float] = None) -> Dict[str, Any]:
    """Start the sampling profiler CLUSTER-WIDE (head locally + a pubsub
    broadcast to every worker).  Returns {"hz": effective}."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    if wr is not None:
        return wr.request("profile", ("start", hz))
    return _rt().profile_start(hz)


def profile_stop() -> Dict[str, Any]:
    """Stop cluster-wide sampling (workers push their final tables)."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    if wr is not None:
        return wr.request("profile", ("stop",))
    return _rt().profile_stop()


def profile_report(
    node: Optional[str] = None, pid: Optional[int] = None
) -> Dict[str, Any]:
    """Merged cluster flamegraph: summed collapsed-stack tables from
    every pushed process + the head's own, with per-process attribution
    rows ({"samples", "processes", "pids", "total_samples"})."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    payload = {"node": node, "pid": pid}
    wr = get_worker_runtime()
    if wr is not None:
        return wr.request("profile", ("report", payload))
    return _rt().profile_report(**payload)


def list_object_refs(limit: int = 1000) -> List[Dict[str, Any]]:
    """Per-object ledger records: size, location, copies, owner refcount,
    holders (process/node/pid/count/creation site), age, leak verdict —
    the raw rows memory_summary aggregates."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    if wr is not None:
        return wr.request("list_object_refs", {"limit": limit})
    return _rt().memory_records(limit=limit)
