"""multiprocessing.Pool API over the task runtime.

ray: python/ray/util/multiprocessing/pool.py — the drop-in Pool that turns
`pool.map(f, xs)` into cluster tasks.  Re-built on this runtime's task
surface: each submission is one @remote task (the scheduler does the
load-balancing the reference's per-actor round-robin does by hand), and
laziness/chunking match the stdlib contract.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    """stdlib-shaped handle over object refs."""

    def __init__(self, refs: List[Any], single: bool, chunked: bool = False):
        self._refs = refs
        self._single = single
        self._chunked = chunked

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        if self._chunked:
            out = [x for chunk in out for x in chunk]
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(
            self._refs, num_returns=len(self._refs), timeout=0
        )
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """Drop-in for multiprocessing.Pool (the reference's util.multiprocessing).

    processes bounds in-flight tasks (backpressure), not worker count —
    the runtime's worker pool is shared cluster-wide.
    """

    def __init__(self, processes: Optional[int] = None, **_compat):
        ray_tpu.init(ignore_reinit_error=True)
        self._max_inflight = processes or 0
        self._closed = False

    # -- helpers ----------------------------------------------------------
    def _task(self, func: Callable):
        return ray_tpu.remote(func)

    def _chunks(self, it: Iterable, size: int):
        it = iter(it)
        while True:
            chunk = list(itertools.islice(it, size))
            if not chunk:
                return
            yield chunk

    def _submit_all(self, task, chunks: List[list]) -> List[Any]:
        refs = []
        for chunk in chunks:
            if self._max_inflight and len(refs) >= self._max_inflight:
                # Backpressure: wait for ONE in-flight chunk before the next
                # submit, bounding cluster memory like a real pool bounds
                # concurrency.
                ray_tpu.wait(refs, num_returns=len(refs) - self._max_inflight + 1)
            refs.append(task.remote(chunk))
        return refs

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    # -- stdlib surface ---------------------------------------------------
    def apply(self, func, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args=(), kwds=None):
        self._check_open()
        ref = self._task(func).remote(*args, **(kwds or {}))
        return AsyncResult([ref], single=True)

    def map(self, func, iterable, chunksize: Optional[int] = None):
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable, chunksize: Optional[int] = None):
        self._check_open()
        items = list(iterable)
        size = chunksize or max(1, len(items) // 64 or 1)

        def run_chunk(chunk):
            return [func(x) for x in chunk]

        refs = self._submit_all(self._task(run_chunk), list(self._chunks(items, size)))
        return AsyncResult(refs, single=False, chunked=True)

    def starmap(self, func, iterable, chunksize: Optional[int] = None):
        return self.map(lambda args: func(*args), iterable, chunksize)

    def _chunk_task(self, func: Callable):
        def run_chunk(chunk):
            return [func(x) for x in chunk]

        return self._task(run_chunk)

    def imap(self, func, iterable, chunksize: Optional[int] = None):
        """Lazy iterator in ORDER; at most `processes` chunks in flight
        and the input consumed lazily (the class's backpressure contract —
        a huge iterable never floods the cluster)."""
        self._check_open()  # at CALL time, like the stdlib
        task = self._chunk_task(func)
        window = self._max_inflight or 64

        def gen():
            from collections import deque

            refs = deque()
            for chunk in self._chunks(iterable, chunksize or 1):
                refs.append(task.remote(chunk))
                if len(refs) >= window:
                    # Ordered: drain the HEAD, blocking until it's done.
                    yield from ray_tpu.get(refs.popleft())
            while refs:
                yield from ray_tpu.get(refs.popleft())

        return gen()

    def imap_unordered(self, func, iterable, chunksize: Optional[int] = None):
        """Lazy iterator in COMPLETION order; same in-flight window."""
        self._check_open()
        task = self._chunk_task(func)
        window = self._max_inflight or 64

        def gen():
            pending: List[Any] = []
            for chunk in self._chunks(iterable, chunksize or 1):
                pending.append(task.remote(chunk))
                if len(pending) >= window:
                    done, pending[:] = ray_tpu.wait(pending, num_returns=1)
                    for r in done:
                        yield from ray_tpu.get(r)
            while pending:
                done, pending[:] = ray_tpu.wait(pending, num_returns=1)
                for r in done:
                    yield from ray_tpu.get(r)

        return gen()

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
