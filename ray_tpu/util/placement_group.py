"""Placement groups: gang resource reservation
(ray: python/ray/util/placement_group.py:128, strategies :142-146).

Strategies: PACK, SPREAD, STRICT_PACK, STRICT_SPREAD, plus the TPU-native
"MESH" strategy (bundles land on an ICI-contiguous set of hosts; see
ray_tpu/_private/scheduler.py).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.client import client

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD", "MESH")


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_seconds
        delay = 0.002
        while time.monotonic() < deadline:
            if client.pg_state(self.id) == "CREATED":
                return True
            time.sleep(delay)
            delay = min(delay * 2, 0.1)
        return client.pg_state(self.id) == "CREATED"

    def ready(self) -> bool:
        return client.pg_state(self.id) == "CREATED"

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy}; valid: {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    pg_id = client.pg_create(bundles, strategy, name)
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    client.pg_remove(pg.id)
