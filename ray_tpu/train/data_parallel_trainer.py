"""DataParallelTrainer: N SPMD worker actors run one train function.

ray: python/ray/train/data_parallel_trainer.py:56 (DataParallelTrainer,
training_loop :385) + base_trainer.py:52/:538 (fit).  Simplifications by
design: fit() drives the BackendExecutor directly (the reference wraps every
trainer in a Tune Tuner even for a single run); Tune integration comes via
ray_tpu.tune wrapping the trainer instead — one direction, not a cycle.

Failure model (SURVEY.md §7 hard parts): a rank failure kills the SPMD
program, so FailureConfig.max_failures restarts the WHOLE worker group from
the latest checkpoint — elastic re-mesh, not per-worker restart.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingFailedError


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.backend_config = backend_config or JaxConfig()
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        # {name: ray_tpu.data.Dataset} — split equally across ranks at fit()
        # (equal row counts: unequal SPMD shards hang compiled collectives),
        # exposed in workers via session.get_dataset_shard(name)
        # (ray: DataParallelTrainer datasets= / session.get_dataset_shard).
        self.datasets = datasets

    def fit(self) -> Result:
        import ray_tpu

        ray_tpu._auto_init()
        failure = self.run_config.failure_config or FailureConfig()
        ckpt_cfg = self.run_config.checkpoint_config or CheckpointConfig()
        attempts_left = failure.max_failures
        latest_ckpt = self.resume_from_checkpoint
        history: list = []
        # History length at the moment of the last checkpoint: on group
        # restart the resumed run re-reports steps after that checkpoint, so
        # anything past this mark belongs to the failed attempt and must be
        # dropped to keep metrics_history free of duplicate steps.
        ckpt_history_len = 0
        last_error: Optional[Exception] = None

        while True:
            executor = BackendExecutor(self.backend_config, self.scaling_config)
            try:
                executor.start()

                def on_report(rank: int, rep: Dict):
                    nonlocal latest_ckpt, ckpt_history_len
                    if rank == 0:
                        history.append(rep["metrics"])
                        # Inside a tune trial actor: stream rank-0 reports up
                        # to the trial session so ASHA/PBT see intermediate
                        # results (ray: base_trainer.py:538 wraps trainers in
                        # trainables for the same effect).
                        from ray_tpu.train import session as _sess

                        if _sess._session is not None:
                            _sess._session.report(
                                rep["metrics"], checkpoint=rep.get("checkpoint")
                            )
                    if rep.get("checkpoint") is not None:
                        latest_ckpt = rep["checkpoint"]
                        ckpt_history_len = len(history)

                shards = None
                if self.datasets:
                    n = self.scaling_config.num_workers
                    shards = {
                        name: ds.split(n, equal=True)
                        for name, ds in self.datasets.items()
                    }
                reports = executor.run_training(
                    self.train_loop_per_worker,
                    config=self.train_loop_config,
                    resume_checkpoint=latest_ckpt,
                    on_report=on_report,
                    dataset_shards=shards,
                )
                metrics = history[-1] if history else {}
                return Result(
                    metrics=metrics,
                    checkpoint=latest_ckpt,
                    metrics_history=history,
                )
            except TrainingFailedError as e:
                last_error = e
                if attempts_left == 0:
                    return Result(
                        metrics=history[-1] if history else None,
                        checkpoint=latest_ckpt,
                        error=e,
                        metrics_history=history,
                    )
                if attempts_left > 0:
                    attempts_left -= 1
                # group restart from latest checkpoint (elastic re-mesh);
                # drop the failed attempt's post-checkpoint metrics
                del history[ckpt_history_len:]
            finally:
                executor.shutdown()


class JaxTrainer(DataParallelTrainer):
    """Sugar: DataParallelTrainer with the SPMD mesh backend preconfigured.

    The TPU-native answer to the reference's TorchTrainer
    (ray: python/ray/train/torch/torch_trainer.py): instead of wrapping the
    model in DDP, the train loop builds a global mesh (jax.devices() spans
    every worker after backend setup) and pjits its step.
    """

    def __init__(self, train_loop_per_worker: Callable, **kwargs):
        kwargs.setdefault("backend_config", JaxConfig())
        super().__init__(train_loop_per_worker, **kwargs)
