"""DataParallelTrainer: N SPMD worker actors run one train function.

ray: python/ray/train/data_parallel_trainer.py:56 (DataParallelTrainer,
training_loop :385) + base_trainer.py:52/:538 (fit).  Simplifications by
design: fit() drives the BackendExecutor directly (the reference wraps every
trainer in a Tune Tuner even for a single run); Tune integration comes via
ray_tpu.tune wrapping the trainer instead — one direction, not a cycle.

Failure model (SURVEY.md §7 hard parts): a rank failure kills the SPMD
program, so FailureConfig.max_failures restarts the WHOLE worker group from
the latest checkpoint — elastic re-mesh, not per-worker restart.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    RemeshScaleUp,
    TrainingFailedError,
)
from ray_tpu.util import tracing


def _observe_remesh(stages: Dict[str, float]) -> float:
    """Fold one elastic-recovery episode into the remesh_seconds histogram:
    one sample per stage (detect/teardown/replan/respawn/resume) plus the
    end-to-end total, so p50/p99 recovery time is attributable per stage."""
    from ray_tpu._private import telemetry

    h = telemetry.remesh_histogram()
    total = 0.0
    for stage, dur in stages.items():
        d = max(float(dur), 0.0)
        h.observe(d, tags={"stage": stage})
        total += d
    h.observe(total, tags={"stage": "total"})
    return total


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.backend_config = backend_config or JaxConfig()
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        # {name: ray_tpu.data.Dataset} — split equally across ranks at fit()
        # (equal row counts: unequal SPMD shards hang compiled collectives),
        # exposed in workers via session.get_dataset_shard(name)
        # (ray: DataParallelTrainer datasets= / session.get_dataset_shard).
        self.datasets = datasets

    def fit(self) -> Result:
        import ray_tpu

        ray_tpu._auto_init()
        failure = self.run_config.failure_config or FailureConfig()
        ckpt_cfg = self.run_config.checkpoint_config or CheckpointConfig()
        attempts_left = failure.max_failures
        latest_ckpt = self.resume_from_checkpoint
        history: list = []
        # History length at the moment of the last checkpoint: on group
        # restart the resumed run re-reports steps after that checkpoint, so
        # anything past this mark belongs to the failed attempt and must be
        # dropped to keep metrics_history free of duplicate steps.
        ckpt_history_len = 0
        last_error: Optional[Exception] = None
        # ONE executor for the whole fit: its placement group is the elastic
        # gang and must survive group restarts (re-mesh respawns workers
        # into the SAME re-planned reservation).
        executor = BackendExecutor(self.backend_config, self.scaling_config)
        num_workers = self.scaling_config.num_workers
        # In-flight re-mesh episode (stage durations + span context); the
        # "resume" stage closes at the first report of the restarted run.
        remesh: Optional[Dict[str, Any]] = None

        def finalize_remesh():
            nonlocal remesh
            if remesh is None:
                return
            ep, remesh = remesh, None
            mono_now = time.monotonic()
            ep["stages"]["resume"] = mono_now - ep["respawn_end_mono"]
            _observe_remesh(ep["stages"])
            if tracing.is_enabled():
                # Detect started on the head and resume closed inside a
                # report callback — record those (and the parent span whose
                # ids the live teardown/replan/respawn spans parented to)
                # retroactively, mapping monotonic stamps onto the epoch
                # clock for the merged chrome timeline.
                epoch_now = time.time()

                def _at(mono: float) -> float:
                    return epoch_now - (mono_now - mono)

                t0 = ep["t0_mono"]
                tracing.record_span(
                    "train::remesh::detect",
                    _at(t0), _at(t0 + ep["stages"]["detect"]),
                    parent=ep["ctx"],
                )
                tracing.record_span(
                    "train::remesh::resume",
                    _at(ep["respawn_end_mono"]), _at(mono_now),
                    parent=ep["ctx"],
                )
                tracing.record_span(
                    "train::remesh", _at(t0), _at(mono_now), ctx=ep["ctx"],
                    attrs={
                        "direction": ep["direction"],
                        "world_size": executor.num_started_workers,
                        **{
                            f"{k}_s": round(v, 4)
                            for k, v in ep["stages"].items()
                        },
                    },
                )

        def remesh_restart(direction: str, caught_mono: float):
            """One recovery episode: tear down the torn group, wait for the
            head to re-form the gang (shrink: re-planned box at N-1 or a
            replacement host; expand: pg_reshape back to full size), and
            respawn workers into it — measuring each stage."""
            nonlocal num_workers, remesh
            info = executor.pg_info() or {}
            since = info.get("reshaping_since")
            # detect = head noticed the loss -> driver caught the failure
            # (monotonic is system-wide on Linux).  Scale-ups start at the
            # driver: the head only enters RESHAPING after pg_reshape.
            t0 = caught_mono
            if direction == "shrink" and isinstance(since, (int, float)):
                t0 = min(since, caught_mono)
            ctx = {
                "trace_id": os.urandom(16).hex(),
                "span_id": os.urandom(8).hex(),
            }
            stages = {"detect": caught_mono - t0}
            t = time.monotonic()
            with tracing.span("train::remesh::teardown", parent=ctx):
                executor.stop_workers()
            stages["teardown"] = time.monotonic() - t
            t = time.monotonic()
            with tracing.span("train::remesh::replan", parent=ctx):
                if direction == "expand":
                    executor.request_scale_up()
                new_info = executor.wait_remesh()
            stages["replan"] = time.monotonic() - t
            t = time.monotonic()
            with tracing.span("train::remesh::respawn", parent=ctx):
                executor.start(num_workers=new_info["size"])
                num_workers = executor.num_started_workers
            end = time.monotonic()
            stages["respawn"] = end - t
            remesh = {
                "stages": stages, "ctx": ctx, "t0_mono": t0,
                "respawn_end_mono": end, "direction": direction,
            }

        def on_report(rank: int, rep: Dict):
            nonlocal latest_ckpt, ckpt_history_len
            finalize_remesh()  # first report after a re-mesh: resume done
            if rank == 0:
                history.append(rep["metrics"])
                # Inside a tune trial actor: stream rank-0 reports up
                # to the trial session so ASHA/PBT see intermediate
                # results (ray: base_trainer.py:538 wraps trainers in
                # trainables for the same effect).
                from ray_tpu.train import session as _sess

                if _sess._session is not None:
                    _sess._session.report(
                        rep["metrics"], checkpoint=rep.get("checkpoint")
                    )
            if rep.get("checkpoint") is not None:
                latest_ckpt = rep["checkpoint"]
                ckpt_history_len = len(history)

        try:
            while True:
                try:
                    executor.start(num_workers=num_workers)
                    num_workers = executor.num_started_workers

                    shards = None
                    if self.datasets:
                        # Split by the ACTUAL world size: a shrunk elastic
                        # gang re-splits so every row is still covered.
                        n = executor.num_started_workers or num_workers
                        shards = {
                            name: ds.split(n, equal=True)
                            for name, ds in self.datasets.items()
                        }
                    reports = executor.run_training(
                        self.train_loop_per_worker,
                        config=self.train_loop_config,
                        resume_checkpoint=latest_ckpt,
                        on_report=on_report,
                        dataset_shards=shards,
                    )
                    finalize_remesh()  # run ended before reporting again
                    metrics = history[-1] if history else {}
                    return Result(
                        metrics=metrics,
                        checkpoint=latest_ckpt,
                        metrics_history=history,
                    )
                except (RemeshScaleUp, TrainingFailedError) as e:
                    caught = time.monotonic()
                    is_remesh = isinstance(e, RemeshScaleUp) or (
                        executor.remesh_in_progress()
                    )
                    if is_remesh:
                        # Elastic re-mesh is recovery, not failure: restart
                        # from the latest checkpoint WITHOUT charging the
                        # failure budget.
                        direction = (
                            "expand" if isinstance(e, RemeshScaleUp)
                            else "shrink"
                        )
                        try:
                            remesh_restart(direction, caught)
                            del history[ckpt_history_len:]
                            continue
                        except TrainingFailedError as e2:
                            e = e2  # re-mesh itself failed: charge budget
                    if isinstance(e, RemeshScaleUp):  # restart failed above
                        e = TrainingFailedError(str(e))
                    last_error = e
                    if attempts_left == 0:
                        return Result(
                            metrics=history[-1] if history else None,
                            checkpoint=latest_ckpt,
                            error=e,
                            metrics_history=history,
                        )
                    if attempts_left > 0:
                        attempts_left -= 1
                    # group restart from latest checkpoint; drop the failed
                    # attempt's post-checkpoint metrics
                    executor.stop_workers()
                    del history[ckpt_history_len:]
        finally:
            executor.shutdown()


class JaxTrainer(DataParallelTrainer):
    """Sugar: DataParallelTrainer with the SPMD mesh backend preconfigured.

    The TPU-native answer to the reference's TorchTrainer
    (ray: python/ray/train/torch/torch_trainer.py): instead of wrapping the
    model in DDP, the train loop builds a global mesh (jax.devices() spans
    every worker after backend setup) and pjits its step.
    """

    def __init__(self, train_loop_per_worker: Callable, **kwargs):
        kwargs.setdefault("backend_config", JaxConfig())
        super().__init__(train_loop_per_worker, **kwargs)
