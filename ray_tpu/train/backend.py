"""Train backends: per-framework worker-group setup.

ray: python/ray/train/backend.py (Backend/BackendConfig) and
train/torch/config.py:69 (_setup_torch_process_group — rank-0 address
broadcast, then dist.init_process_group :113).  TPU-native: the process
group IS the XLA runtime — JaxConfig's on_start picks a coordinator on rank
0 and every worker calls jax.distributed.initialize, after which one pjit
program spans all workers' chips over ICI/DCN.  No NCCL library, no wrapper:
collectives are compiled (SURVEY.md §5.8).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.train.worker_group import WorkerGroup


@dataclasses.dataclass
class BackendConfig:
    """Base backend config (ray: python/ray/train/backend.py)."""

    def backend_cls(self):
        return Backend


class Backend:
    """Framework setup/teardown hooks around a WorkerGroup."""

    def on_start(self, worker_group: WorkerGroup, backend_config: "BackendConfig"):
        pass

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: "BackendConfig"):
        pass


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """SPMD mesh bootstrap over the worker group.

    coordinator_port 0 = pick a free port on rank 0's host.
    platform: force a jax platform in workers (tests use "cpu").
    """

    coordinator_port: int = 0
    platform: Optional[str] = None

    def backend_cls(self):
        return _JaxBackend


def _pick_coordinator(port: int) -> str:
    from ray_tpu.parallel.bootstrap import pick_coordinator_address

    return pick_coordinator_address(port)


def _init_jax_distributed(coordinator: str, world_size: int, rank: int, platform):
    import os

    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if world_size > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=world_size,
            process_id=rank,
        )
    return {
        "rank": rank,
        "global_devices": len(jax.devices()),
        "local_devices": jax.local_device_count(),
    }


class _JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, backend_config: JaxConfig):
        coordinator = worker_group.execute_single(
            0, _pick_coordinator, backend_config.coordinator_port, timeout=60
        )
        # All workers join the XLA coordination service (the analogue of the
        # reference broadcasting rank-0's addr then init_process_group).
        return self._start_all(worker_group, coordinator, backend_config)

    @staticmethod
    def _start_all(worker_group: WorkerGroup, coordinator: str, cfg: JaxConfig):
        import ray_tpu

        n = worker_group.num_workers
        refs = [
            w.run_fn.remote(_init_jax_distributed, coordinator, n, i, cfg.platform)
            for i, w in enumerate(worker_group.workers)
        ]
        return ray_tpu.get(refs, timeout=300)

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: JaxConfig):
        def _shut():
            import jax

            try:
                jax.distributed.shutdown()
            except Exception:
                pass

        try:
            worker_group.execute(_shut, timeout=30)
        except Exception:
            pass
