"""ray_tpu.train: distributed SPMD training over the actor runtime.

ray: python/ray/train/ — trainers spawn a gang of worker actors, the backend
joins them into one process group, the user loop reports metrics/checkpoints
(SURVEY.md §3.5).  TPU-native: the "process group" is the multi-host XLA
runtime; gradient communication is compiled into the train step, not a
runtime collective library.
"""

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result
from ray_tpu.train import session
from ray_tpu.train.backend import Backend, BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingFailedError
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer, JaxTrainer
from ray_tpu.train.session import (
    get_checkpoint,
    get_dataset_shard,
    get_world_rank,
    get_world_size,
    report,
)
from ray_tpu.train.worker_group import WorkerGroup

__all__ = [
    "Backend",
    "BackendConfig",
    "BackendExecutor",
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainingFailedError",
    "WorkerGroup",
    "get_checkpoint",
    "get_dataset_shard",
    "get_world_rank",
    "get_world_size",
    "report",
    "session",
]
