"""Torch backend: process-group setup across the worker group.

ray: python/ray/train/torch/config.py (_TorchBackend.on_start :145,
_setup_torch_process_group :69, dist.init_process_group :113).  Rank 0
picks a free TCP port; every worker joins the gloo group (CPU containers;
NCCL has no place on a TPU host).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig


def _pick_free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _setup_process_group(
    master_addr: str, master_port: int, rank: int, world_size: int, backend: str,
    timeout_s: float,
):
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    dist.init_process_group(
        backend=backend,
        rank=rank,
        world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s),
    )


def _teardown_process_group():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()


class _TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: "TorchConfig"):
        import ray_tpu

        master_addr = "127.0.0.1"
        master_port = worker_group.execute_single(0, _pick_free_port)
        # join everyone concurrently: init_process_group blocks until all
        # ranks arrive, so this must NOT be a serial execute()
        refs = [
            w.run_fn.remote(
                _setup_process_group,
                master_addr,
                master_port,
                i,
                worker_group.num_workers,
                backend_config.backend,
                backend_config.timeout_s,
            )
            for i, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs, timeout=backend_config.timeout_s + 30)

    def on_shutdown(self, worker_group, backend_config: "TorchConfig"):
        worker_group.execute(_teardown_process_group, timeout=30)


@dataclasses.dataclass
class TorchConfig(BackendConfig):
    """ray: train/torch/config.py TorchConfig."""

    backend: str = "gloo"
    timeout_s: float = 120.0

    def backend_cls(self):
        return _TorchBackend
