"""TorchTrainer: DataParallelTrainer with the torch/gloo backend.

ray: python/ray/train/torch/torch_trainer.py — same construction surface;
the train_loop_per_worker runs with torch.distributed initialized across
the worker group and uses prepare_model/prepare_data_loader + session
reporting exactly like the reference.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.torch.config import TorchConfig


class TorchTrainer(DataParallelTrainer):
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        torch_config: Optional[TorchConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=torch_config or TorchConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint,
            datasets=datasets,
        )
