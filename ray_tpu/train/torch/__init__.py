"""ray_tpu.train.torch — torch DDP training on the actor runtime.

ray: python/ray/train/torch/ (TorchTrainer, config.py:69
_setup_torch_process_group, train_loop_utils.py prepare_model).  JAX is the
TPU compute path; this backend exists for reference-parity — users porting
TorchTrainer workloads get the same surface, running torch.distributed
with the gloo backend across the SPMD worker group.
"""

from ray_tpu.train.torch.config import TorchConfig
from ray_tpu.train.torch.torch_trainer import TorchTrainer
from ray_tpu.train.torch.train_loop_utils import prepare_data_loader, prepare_model

__all__ = ["TorchConfig", "TorchTrainer", "prepare_data_loader", "prepare_model"]
