"""prepare_model / prepare_data_loader for torch train loops.

ray: python/ray/train/torch/train_loop_utils.py:92-98 (DDP/FSDP wrap) —
reduced to the CPU/gloo case this backend targets: DDP wrap + a
DistributedSampler-equipped loader.
"""

from __future__ import annotations


def prepare_model(model, parallel_strategy: str = "ddp"):
    """Wrap an nn.Module for distributed training
    (ray: prepare_model :92-98)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel as DDP

    if parallel_strategy not in ("ddp", None):
        raise ValueError(
            f"parallel_strategy {parallel_strategy!r} unsupported here: this "
            "backend is the CPU/gloo parity path (TPU training is JaxTrainer)"
        )
    if dist.is_initialized() and dist.get_world_size() > 1 and parallel_strategy:
        return DDP(model)
    return model


def prepare_data_loader(dataset, batch_size: int, shuffle: bool = True):
    """DataLoader with a per-rank DistributedSampler
    (ray: prepare_data_loader)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    sampler = None
    if dist.is_initialized() and dist.get_world_size() > 1:
        sampler = DistributedSampler(dataset, shuffle=shuffle)
        shuffle = False
    return DataLoader(
        dataset, batch_size=batch_size, shuffle=shuffle, sampler=sampler
    )
