"""Per-worker train session: report queue + rank info.

ray: python/ray/train/_internal/session.py:63 (_TrainSession, report queue
:120/:171) and python/ray/air/session.py (the user-facing facade).  The user
train loop calls session.report(metrics, checkpoint=...) — reports buffer in
the worker actor and are drained by the driver's BackendExecutor poll loop.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint

_session: Optional["TrainSession"] = None


class TrainSession:
    def __init__(
        self,
        rank: int,
        world_size: int,
        local_rank: int = 0,
        resume_checkpoint: Optional[Checkpoint] = None,
        experiment_name: str = "train",
        dataset_shards: Optional[Dict[str, Any]] = None,
    ):
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.resume_checkpoint = resume_checkpoint
        self.experiment_name = experiment_name
        self.dataset_shards = dataset_shards or {}
        self._lock = threading.Lock()
        self._reports: List[Dict[str, Any]] = []
        self.done = False
        self.error: Optional[BaseException] = None

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
        with self._lock:
            self._reports.append({"metrics": dict(metrics), "checkpoint": checkpoint})

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = self._reports
            self._reports = []
            return out


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session active — this API must run inside a train worker"
        )
    return _session


def shutdown_session():
    global _session
    _session = None


# -- user-facing facade (ray: python/ray/air/session.py) -------------------


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None) -> None:
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().resume_checkpoint


def get_dataset_shard(name: str = "train"):
    """This rank's Dataset shard (ray: session.get_dataset_shard) — block
    refs resolve worker-side, so iteration never round-trips the driver."""
    shards = get_session().dataset_shards
    if name not in shards:
        raise KeyError(
            f"no dataset shard {name!r}; trainer datasets: {sorted(shards)}"
        )
    return shards[name]


def get_world_rank() -> int:
    return get_session().rank


def get_world_size() -> int:
    return get_session().world_size


def get_local_rank() -> int:
    return get_session().local_rank


def get_experiment_name() -> str:
    return get_session().experiment_name
