"""BackendExecutor: owns the worker group + backend lifecycle and the
training poll loop.

ray: python/ray/train/_internal/backend_executor.py:43 (start :94,
start_training :315).  Differences by design: reports are pulled via actor
polling (the worker actors run the blocking train fn in one concurrency slot
and answer poll() in the other), and failure handling restarts the WHOLE
group — an SPMD mesh program cannot lose a single rank (SURVEY.md §7
"SPMD meets actors").
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: Optional[ScalingConfig] = None,
    ):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()()
        self.scaling = scaling_config or ScalingConfig()
        self.worker_group: Optional[WorkerGroup] = None
        self._pg = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        sc = self.scaling
        if sc.num_workers > 1:
            # Gang-reserve the workers' resources (ray: Train reserves a PG
            # per trial via Tune — base_trainer.py:52 path).
            from ray_tpu.util.placement_group import placement_group

            bundles = [sc.worker_resources() for _ in range(sc.num_workers)]
            self._pg = placement_group(bundles, strategy=sc.placement_strategy)
            self._pg.wait(timeout_seconds=60)
        self.worker_group = WorkerGroup(
            sc.num_workers, sc.worker_resources(), placement_group=self._pg
        )
        self.backend.on_start(self.worker_group, self.backend_config)

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group, self.backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None

    # -- training ---------------------------------------------------------
    def run_training(
        self,
        train_fn: Callable,
        config: Optional[Dict[str, Any]] = None,
        resume_checkpoint: Optional[Checkpoint] = None,
        on_report: Optional[Callable[[int, Dict], None]] = None,
        poll_interval: float = 0.05,
        dataset_shards: Optional[Dict[str, List[Any]]] = None,
    ) -> List[Dict[str, Any]]:
        """Run train_fn on all workers; stream reports; return each rank's
        report list.  Raises TrainingFailedError on any rank failure.

        dataset_shards: {name: [per-rank Dataset shard]} — rank i receives
        shard i under session.get_dataset_shard(name)."""
        wg = self.worker_group
        assert wg is not None, "call start() first"
        done_refs = [
            w.run_train_fn.remote(
                train_fn,
                config,
                resume_checkpoint,
                {name: shards[i] for name, shards in (dataset_shards or {}).items()},
            )
            for i, w in enumerate(wg.workers)
        ]
        all_reports: List[List[Dict]] = [[] for _ in wg.workers]
        finished = [False] * len(wg.workers)
        error: Optional[BaseException] = None
        while not all(finished) and error is None:
            time.sleep(poll_interval)
            try:
                polls = ray_tpu.get(
                    [w.poll.remote() for w in wg.workers], timeout=60
                )
            except Exception as e:
                # A dead worker actor (crash/OOM/preemption) must surface as
                # TrainingFailedError so FailureConfig group-restart applies,
                # not as a raw ActorDiedError escaping fit().
                raise TrainingFailedError(
                    f"train worker died during poll: {e}"
                ) from e
            for i, p in enumerate(polls):
                for rep in p["reports"]:
                    all_reports[i].append(rep)
                    if on_report is not None:
                        on_report(i, rep)
            # completion/errors via the run refs (non-blocking check)
            ready, _ = ray_tpu.wait(done_refs, num_returns=len(done_refs), timeout=0)
            for i, r in enumerate(done_refs):
                if r in ready and not finished[i]:
                    try:
                        ray_tpu.get(r, timeout=1)
                        finished[i] = True
                    except Exception as e:
                        error = e
                        break
        if error is not None:
            raise TrainingFailedError(str(error)) from error
        # final drain
        try:
            polls = ray_tpu.get([w.poll.remote() for w in wg.workers], timeout=60)
        except Exception as e:
            raise TrainingFailedError(
                f"train worker died during final report drain: {e}"
            ) from e
        for i, p in enumerate(polls):
            for rep in p["reports"]:
                all_reports[i].append(rep)
                if on_report is not None:
                    on_report(i, rep)
        return all_reports
