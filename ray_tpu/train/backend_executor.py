"""BackendExecutor: owns the worker group + backend lifecycle and the
training poll loop.

ray: python/ray/train/_internal/backend_executor.py:43 (start :94,
start_training :315).  Differences by design: reports are pulled via actor
polling (the worker actors run the blocking train fn in one concurrency slot
and answer poll() in the other), and failure handling restarts the WHOLE
group — an SPMD mesh program cannot lose a single rank (SURVEY.md §7
"SPMD meets actors").
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class RemeshScaleUp(Exception):
    """Internal control flow, not a failure: the head signalled that a
    shrunk MESH gang can scale back to full size (pg_info scale_up_ready).
    run_training raises it so the trainer can tear down, pg_reshape, and
    restart at the original world size from the latest checkpoint."""


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: Optional[ScalingConfig] = None,
    ):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()()
        self.scaling = scaling_config or ScalingConfig()
        self.worker_group: Optional[WorkerGroup] = None
        self._pg = None
        # Elastic MESH gangs: generation of the reservation the current
        # worker group was spawned into; the head bumps it on every re-mesh.
        self._elastic = False
        self._generation = 0
        self.num_started_workers = 0
        # How long start() waits for the gang reservation before failing
        # with the PG state + unplaceable bundles (tests shrink this).
        self.pg_wait_timeout_s = 60.0

    # -- lifecycle --------------------------------------------------------
    def start(self, num_workers: Optional[int] = None):
        """Spawn the worker group (no-op if already started).

        The placement group is created ONCE and survives stop_workers():
        elastic restarts re-spawn workers into the re-meshed gang.
        num_workers overrides the scaling config's count (elastic MESH
        gangs restart at the gang's current — possibly shrunk — size)."""
        if self.worker_group is not None:
            return
        sc = self.scaling
        n = sc.num_workers if num_workers is None else num_workers
        if sc.num_workers > 1:
            # Gang-reserve the workers' resources (ray: Train reserves a PG
            # per trial via Tune — base_trainer.py:52 path).
            if self._pg is None:
                from ray_tpu.util.placement_group import placement_group

                bundles = [sc.worker_resources() for _ in range(sc.num_workers)]
                self._pg = placement_group(
                    bundles, strategy=sc.placement_strategy
                )
            if not self._pg.wait(timeout_seconds=self.pg_wait_timeout_s):
                info = self.pg_info() or {}
                placed = set(info.get("bundle_nodes") or {})
                unplaced = [
                    i
                    for i in range(len(self._pg.bundle_specs))
                    if i not in placed
                ]
                raise TrainingFailedError(
                    f"placement group {self._pg.id} not ready after "
                    f"{self.pg_wait_timeout_s:.0f}s: "
                    f"state={info.get('state') or 'UNKNOWN'}, unplaceable "
                    f"bundles {unplaced} of {self._pg.bundle_specs}; the "
                    "cluster cannot satisfy the reservation — check node "
                    "resources"
                    + (
                        " and mesh_coord labels"
                        if sc.placement_strategy == "MESH"
                        else ""
                    )
                )
            self._elastic = sc.placement_strategy == "MESH"
            info = self.pg_info() or {}
            self._generation = info.get("generation", 0)
            if self._elastic:
                n = min(n, info.get("size", n))
        self.num_started_workers = n
        self.worker_group = WorkerGroup(
            n, sc.worker_resources(), placement_group=self._pg
        )
        self.backend.on_start(self.worker_group, self.backend_config)

    def stop_workers(self):
        """Tear down the worker group KEEPING the placement group — the
        elastic-restart path re-spawns workers into the re-meshed gang."""
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group, self.backend_config)
            except Exception:
                pass
            try:
                self.worker_group.shutdown()
            except Exception:
                pass  # gang actors may already be dead (head killed them)
            self.worker_group = None

    def shutdown(self):
        self.stop_workers()
        if self._pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None

    # -- elastic re-mesh ---------------------------------------------------
    def pg_info(self) -> Optional[Dict[str, Any]]:
        if self._pg is None:
            return None
        from ray_tpu._private.client import client

        return client.pg_info(self._pg.id)

    def remesh_in_progress(self) -> bool:
        """True when the gang the current workers were spawned into no
        longer exists: mid-RESHAPING, or already re-formed at a new
        generation."""
        if not self._elastic:
            return False
        info = self.pg_info()
        return bool(info) and (
            info["state"] == "RESHAPING"
            or info["generation"] != self._generation
        )

    def wait_remesh(self, timeout_seconds: Optional[float] = None) -> Dict:
        """Block until the gang re-forms (CREATED at a new generation);
        returns the final pg_info.  Default timeout covers two head-side
        wait-then-shrink windows plus placement slack."""
        if timeout_seconds is None:
            from ray_tpu._private import config as _config

            timeout_seconds = 2.0 * float(_config.get("remesh_wait_s")) + 60.0
        deadline = time.monotonic() + timeout_seconds
        delay = 0.01
        while True:
            info = self.pg_info()
            if info is None or info["state"] == "REMOVED":
                raise TrainingFailedError(
                    "placement group removed while waiting for re-mesh"
                )
            if info["state"] == "CREATED" and info["generation"] != self._generation:
                self._generation = info["generation"]
                return info
            if time.monotonic() >= deadline:
                raise TrainingFailedError(
                    f"gang did not re-mesh within {timeout_seconds:.0f}s "
                    f"(state={info['state']}, size={info['size']})"
                )
            time.sleep(delay)
            delay = min(delay * 2, 0.25)

    def request_scale_up(self) -> bool:
        """Ask the head to re-mesh a shrunk gang back to full size."""
        if self._pg is None:
            return False
        from ray_tpu._private.client import client

        return bool(client.pg_reshape(self._pg.id))

    # -- training ---------------------------------------------------------
    def run_training(
        self,
        train_fn: Callable,
        config: Optional[Dict[str, Any]] = None,
        resume_checkpoint: Optional[Checkpoint] = None,
        on_report: Optional[Callable[[int, Dict], None]] = None,
        poll_interval: float = 0.05,
        dataset_shards: Optional[Dict[str, List[Any]]] = None,
    ) -> List[Dict[str, Any]]:
        """Run train_fn on all workers; stream reports; return each rank's
        report list.  Raises TrainingFailedError on any rank failure.

        dataset_shards: {name: [per-rank Dataset shard]} — rank i receives
        shard i under session.get_dataset_shard(name)."""
        wg = self.worker_group
        assert wg is not None, "call start() first"
        done_refs = [
            w.run_train_fn.remote(
                train_fn,
                config,
                resume_checkpoint,
                {name: shards[i] for name, shards in (dataset_shards or {}).items()},
            )
            for i, w in enumerate(wg.workers)
        ]
        all_reports: List[List[Dict]] = [[] for _ in wg.workers]
        finished = [False] * len(wg.workers)
        error: Optional[BaseException] = None
        last_pg_check = time.monotonic()
        while not all(finished) and error is None:
            time.sleep(poll_interval)
            if self._elastic and time.monotonic() - last_pg_check >= 1.0:
                # Shrunk gang: surface the head's scale-up cue so the
                # trainer can reshape back to full size between steps.
                last_pg_check = time.monotonic()
                info = self.pg_info()
                if (
                    info is not None
                    and info["state"] == "CREATED"
                    and info["scale_up_ready"]
                    and self.num_started_workers < info["orig_size"]
                ):
                    raise RemeshScaleUp(
                        f"gang can scale {info['size']} -> {info['orig_size']}"
                    )
            try:
                polls = ray_tpu.get(
                    [w.poll.remote() for w in wg.workers], timeout=60
                )
            except Exception as e:
                # A dead worker actor (crash/OOM/preemption) must surface as
                # TrainingFailedError so FailureConfig group-restart applies,
                # not as a raw ActorDiedError escaping fit().
                raise TrainingFailedError(
                    f"train worker died during poll: {e}"
                ) from e
            for i, p in enumerate(polls):
                for rep in p["reports"]:
                    all_reports[i].append(rep)
                    if on_report is not None:
                        on_report(i, rep)
            # completion/errors via the run refs (non-blocking check)
            ready, _ = ray_tpu.wait(done_refs, num_returns=len(done_refs), timeout=0)
            for i, r in enumerate(done_refs):
                if r in ready and not finished[i]:
                    try:
                        ray_tpu.get(r, timeout=1)
                        finished[i] = True
                    except Exception as e:
                        error = e
                        break
        if error is not None:
            raise TrainingFailedError(str(error)) from error
        # final drain
        try:
            polls = ray_tpu.get([w.poll.remote() for w in wg.workers], timeout=60)
        except Exception as e:
            raise TrainingFailedError(
                f"train worker died during final report drain: {e}"
            ) from e
        for i, p in enumerate(polls):
            for rep in p["reports"]:
                all_reports[i].append(rep)
                if on_report is not None:
                    on_report(i, rep)
        return all_reports
