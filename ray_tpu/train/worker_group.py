"""WorkerGroup: the gang of train-worker actors.

ray: python/ray/train/_internal/worker_group.py:92 (WorkerGroup), :226
(execute), :251 (execute_async).  Workers are ray_tpu actors with
max_concurrency=2 so the driver can poll session reports while the
(blocking) train function runs in the other slot.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.session import TrainSession, init_session


@ray_tpu.remote(max_concurrency=2)
class TrainWorker:
    """One rank of the SPMD train job."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.session: Optional[TrainSession] = None

    # -- backend hooks ----------------------------------------------------
    def run_fn(self, fn: Callable, *args, **kwargs):
        """Execute an arbitrary callable in this worker (backend setup)."""
        return fn(*args, **kwargs)

    def host_info(self) -> Dict[str, Any]:
        import os
        import socket

        return {"hostname": socket.gethostname(), "pid": os.getpid(), "rank": self.rank}

    # -- training ---------------------------------------------------------
    def run_train_fn(
        self,
        train_fn: Callable,
        config: Optional[Dict],
        resume_ckpt,
        dataset_shards: Optional[Dict[str, Any]] = None,
    ):
        self.session = init_session(
            rank=self.rank,
            world_size=self.world_size,
            resume_checkpoint=resume_ckpt,
            dataset_shards=dataset_shards,
        )
        try:
            import inspect

            sig = inspect.signature(train_fn)
            if len(sig.parameters) == 0:
                train_fn()
            else:
                train_fn(config or {})
            self.session.done = True
            return {"ok": True}
        except BaseException as e:  # report, don't kill the actor
            self.session.done = True
            self.session.error = e
            raise

    def poll(self) -> Dict[str, Any]:
        """Drain buffered session.report() payloads (driver poll loop)."""
        if self.session is None:
            return {"reports": [], "done": False}
        return {"reports": self.session.drain(), "done": self.session.done}


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Optional[Dict[str, float]] = None,
        placement_group=None,
    ):
        self.num_workers = num_workers
        res = dict(resources_per_worker or {"CPU": 1.0})
        base: Dict[str, Any] = {
            "num_cpus": res.pop("CPU", 1.0),
            "resources": res or None,
        }
        self.workers = []
        for i in range(num_workers):
            opts = dict(base)
            if placement_group is not None:
                from ray_tpu.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy,
                )

                opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group, placement_group_bundle_index=i
                )
            self.workers.append(TrainWorker.options(**opts).remote(i, num_workers))

    def execute(self, fn: Callable, *args, timeout: Optional[float] = None, **kwargs) -> List[Any]:
        """Run fn on every worker, wait for all (ray: worker_group.py:226)."""
        return ray_tpu.get(
            [w.run_fn.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=timeout,
        )

    def execute_single(self, idx: int, fn: Callable, *args, timeout=None, **kwargs):
        return ray_tpu.get(self.workers[idx].run_fn.remote(fn, *args, **kwargs), timeout=timeout)

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.run_fn.remote(fn, *args, **kwargs) for w in self.workers]

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
