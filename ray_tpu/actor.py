"""Actor API: ActorClass / ActorHandle / ActorMethod.

Mirrors ray: python/ray/actor.py (ActorClass :377, ActorHandle :1022,
ActorMethod :92, exit_actor :1368).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu._private import ids
from ray_tpu._private.client import build_args_blob, client, current_session
from ray_tpu._private.task_spec import TaskSpec


def _public_methods(cls) -> List[str]:
    out = []
    for name in dir(cls):
        if name.startswith("_") and name != "__call__":
            continue
        if callable(getattr(cls, name, None)):
            out.append(name)
    return out


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 max_task_retries: Optional[int] = None,
                 retry_exceptions: bool = False):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._max_task_retries = max_task_retries  # None = actor default
        self._retry_exceptions = retry_exceptions

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._name,
            opts.get("num_returns", self._num_returns),
            opts.get("max_task_retries", self._max_task_retries),
            bool(opts.get("retry_exceptions", self._retry_exceptions)),
        )

    def remote(self, *args, **kwargs):
        return self._handle._actor_method_call(
            self._name, args, kwargs, num_returns=self._num_returns,
            max_task_retries=self._max_task_retries,
            retry_exceptions=self._retry_exceptions,
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._name} cannot be called directly; use "
            f".{self._name}.remote()"
        )


class ActorHandle:
    def __init__(self, actor_id: str, method_names: List[str],
                 max_concurrency: int = 1, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._method_names = list(method_names)
        self._max_concurrency = max_concurrency
        self._max_task_retries = max_task_retries

    @property
    def _id(self) -> str:
        return self._actor_id

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._method_names:
            raise AttributeError(
                f"actor has no method {name!r}; available: {self._method_names}"
            )
        return ActorMethod(self, name)

    def _actor_method_call(self, method: str, args, kwargs, num_returns: int = 1,
                           max_task_retries: Optional[int] = None,
                           retry_exceptions: bool = False):
        blob, contained, deps = build_args_blob(args, kwargs)
        retries = (
            self._max_task_retries if max_task_retries is None else max_task_retries
        )
        spec = TaskSpec(
            task_id=ids.task_id(),
            name=f"{self._actor_id}.{method}",
            fn_id="",
            args_blob=blob,
            contained_refs=contained,
            deps=deps,
            num_returns=num_returns,
            resources={},
            actor_id=self._actor_id,
            method_name=method,
            max_concurrency=self._max_concurrency,
            max_retries=int(retries or 0),
            retry_exceptions=retry_exceptions,
        )
        refs = client.submit_actor_task(spec)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_names,
                              self._max_concurrency, self._max_task_retries))

    def __repr__(self) -> str:
        return f"ActorHandle({self._actor_id})"


class ActorClass:
    def __init__(self, cls, options: Dict[str, Any]):
        self._cls = cls
        self._opts = dict(options)
        self._cls_id: Optional[str] = None
        self._exported_session: Optional[str] = None

    def options(self, **opts) -> "ActorClass":
        return ActorClass(self._cls, {**self._opts, **opts})

    def __call__(self, *a, **kw):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()"
        )

    def _ensure_exported(self) -> str:
        session = current_session()
        if self._cls_id is None or self._exported_session != session:
            blob = cloudpickle.dumps(self._cls)
            self._cls_id = "cls-" + hashlib.sha1(blob).hexdigest()[:16]
            client.export_function(self._cls_id, blob)
            self._exported_session = session
        return self._cls_id

    def remote(self, *args, **kwargs) -> ActorHandle:
        o = self._opts
        if o.get("runtime_env"):
            from ray_tpu._private.runtime_env import validate_runtime_env

            validate_runtime_env(o["runtime_env"])
        name = o.get("name")
        if name and o.get("get_if_exists"):
            try:
                aid, methods, mc, mtr = client.get_named_actor(name, o.get("namespace"))
                return ActorHandle(aid, methods, mc, mtr)
            except Exception:
                pass
        cls_id = self._ensure_exported()
        resources = dict(o.get("resources") or {})
        resources["CPU"] = float(o.get("num_cpus", 1))
        if o.get("num_tpus"):
            resources["TPU"] = float(o["num_tpus"])
        if o.get("num_gpus"):
            resources["GPU"] = float(o["num_gpus"])
        blob, contained, deps = build_args_blob(args, kwargs)
        import inspect

        is_async = any(
            inspect.iscoroutinefunction(getattr(self._cls, m, None))
            for m in _public_methods(self._cls)
        )
        max_concurrency = o.get("max_concurrency", 1000 if is_async else 1)
        spec = TaskSpec(
            task_id=ids.task_id(),
            name=f"{self._cls.__name__}.__init__",
            fn_id=cls_id,
            args_blob=blob,
            contained_refs=contained,
            deps=deps,
            num_returns=1,
            resources=resources,
            actor_id=ids.actor_id(),
            is_actor_creation=True,
            actor_name=name,
            actor_namespace=o.get("namespace"),
            actor_method_names=_public_methods(self._cls),
            max_restarts=int(o.get("max_restarts", 0)),
            max_concurrency=1,  # creation itself is ordered
            actor_max_concurrency=max_concurrency,
            actor_max_task_retries=int(o.get("max_task_retries", 0)),
            scheduling_strategy=o.get("scheduling_strategy"),
            runtime_env=o.get("runtime_env"),
            lifetime=o.get("lifetime"),
        )
        client.create_actor(spec)
        return ActorHandle(spec.actor_id, spec.actor_method_names, max_concurrency,
                           spec.actor_max_task_retries)


def exit_actor():
    """Terminate the current actor from inside one of its methods
    (ray: python/ray/actor.py:1368)."""
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    if wr is None or wr.current_actor_id is None:
        raise RuntimeError("exit_actor() called outside an actor")
    wr.oneway(("actor_exit", wr.current_actor_id))
    raise SystemExit(0)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    aid, methods, mc, mtr = client.get_named_actor(name, namespace)
    # Carry the actor's real concurrency: calls through a looked-up handle
    # must land on the same executor as the creator's (a long-poll parked
    # on a 1-slot FIFO would serialize every other caller behind it).
    return ActorHandle(aid, methods, mc, mtr)
