"""Pluggable control-plane snapshot storage.

ray: src/ray/gcs/store_client/ — the reference's GCS persists its tables
through a StoreClient interface with in-memory and Redis backends
(in_memory_store_client.h, redis_store_client.h).  Ours snapshots the
metadata tables as one document per tick; this module makes WHERE that
document lives pluggable:

  * FileSnapshotStorage  — atomic tmp+rename single file (the default;
    zero dependencies, good for one-host clusters and tests);
  * SqliteSnapshotStorage — a WAL-mode sqlite database (crash-safe
    journaled writes, multiple sessions per db file, the shape an external
    durable store plugs into — the Redis-FT analogue without a Redis
    dependency in this image).

Selected by the gcs_storage_backend config knob (RAY_TPU_GCS_STORAGE_BACKEND).
"""

from __future__ import annotations

import os
import pickle
import sys
from typing import Any, Dict, Optional

from ray_tpu._private import faults

# Version of the snapshot DOCUMENT (not the wire protocol): bumped when
# the snapshot's shape changes incompatibly.  Restore-time mismatch is
# LOUD — a silent clean boot on a version bump would quietly drop
# detached actors / KV / lineage (the round-4 verdict's "pickle can
# silently fail restore" finding); the wire got versioning in round 4,
# this is the storage twin (ray: proto-versioned GCS tables).
SNAPSHOT_VERSION = 1


def _stamp(snap: Dict[str, Any]) -> Dict[str, Any]:
    snap["snapshot_version"] = SNAPSHOT_VERSION
    return snap


def _check(
    snap: Dict[str, Any], session: str, origin: str, set_aside=None
) -> Optional[Dict[str, Any]]:
    """Validate a loaded document; None (with a loud stderr note) when it
    must not replay.  `set_aside()` preserves a version-refused document
    out of the save path — without it, the next snapshot tick would
    overwrite the very state the refusal promised not to lose."""
    ver = snap.get("snapshot_version")
    if ver != SNAPSHOT_VERSION:
        print(
            f"[ray_tpu] REFUSING snapshot restore from {origin}: document "
            f"version {ver!r} != supported {SNAPSHOT_VERSION} — starting "
            "clean; the prior control-plane state (detached actors, KV) "
            f"was NOT restored (kept aside for a matching-version binary)",
            file=sys.stderr,
            flush=True,
        )
        if set_aside is not None:
            try:
                set_aside()
            except Exception:
                pass
        return None
    # Session-scoped storage; a foreign session's snapshot must never
    # replay (the caller also re-checks).
    if snap.get("session") != session:
        return None
    return snap


def _corrupt_note(origin: str, err: Exception) -> None:
    print(
        f"[ray_tpu] snapshot at {origin} is unreadable ({type(err).__name__}: "
        f"{err}) — starting clean; prior control-plane state NOT restored",
        file=sys.stderr,
        flush=True,
    )


class SnapshotStorage:
    """Interface: persist/load one session's snapshot document."""

    def save(self, session: str, snap: Dict[str, Any]) -> None:
        raise NotImplementedError

    def load(self, session: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileSnapshotStorage(SnapshotStorage):
    """One pickle file, atomically replaced per tick."""

    def __init__(self, path: str):
        self.path = path

    def save(self, session: str, snap: Dict[str, Any]) -> None:
        if faults.ENABLED:
            # error -> this tick is skipped (the snapshot loop is
            # best-effort); crash -> head death mid-persist, which the
            # atomic tmp+rename below must survive.
            faults.point("gcs.save", key=session)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(_stamp(snap), f)
        os.replace(tmp, self.path)

    def load(self, session: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path, "rb") as f:
                snap = pickle.load(f)
        except FileNotFoundError:
            return None  # genuinely clean boot
        except Exception as e:  # noqa: BLE001 — unreadable ≠ absent
            # Unreadable is NOT "absent": say so, and keep the evidence
            # aside instead of overwriting it on the next save tick.
            _corrupt_note(self.path, e)
            try:
                os.replace(self.path, self.path + ".corrupt")
            except OSError:
                pass
            return None
        return _check(
            snap, session, self.path,
            set_aside=lambda: os.replace(self.path, self.path + ".refused"),
        )


class SqliteSnapshotStorage(SnapshotStorage):
    """WAL-journaled sqlite table keyed by session name.

    One db can hold many sessions' snapshots; writes are transactional, so
    a crash mid-save leaves the previous snapshot intact (the property the
    reference gets from Redis persistence)."""

    def __init__(self, path: str):
        import sqlite3

        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS snapshots ("
            "session TEXT PRIMARY KEY, snap BLOB, updated REAL)"
        )
        self._conn.commit()
        import threading

        self._lock = threading.Lock()

    def save(self, session: str, snap: Dict[str, Any]) -> None:
        import time

        if faults.ENABLED:
            faults.point("gcs.save", key=session)
        blob = pickle.dumps(_stamp(snap))
        with self._lock:
            self._conn.execute(
                "INSERT INTO snapshots (session, snap, updated) "
                "VALUES (?, ?, ?) ON CONFLICT(session) DO UPDATE SET "
                "snap=excluded.snap, updated=excluded.updated",
                (session, blob, time.time()),
            )
            self._conn.commit()

    def load(self, session: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            row = self._conn.execute(
                "SELECT snap FROM snapshots WHERE session=?", (session,)
            ).fetchone()
        if row is None:
            return None  # genuinely clean boot
        def _aside(suffix: str) -> None:
            with self._lock:
                self._conn.execute(
                    "UPDATE snapshots SET session=? WHERE session=?",
                    (session + suffix, session),
                )
                self._conn.commit()

        try:
            snap = pickle.loads(row[0])
        except Exception as e:  # noqa: BLE001 — unreadable ≠ absent
            _corrupt_note(f"{self.path}:{session}", e)
            try:
                _aside(".corrupt")  # next save tick must not destroy it
            except Exception:
                pass
            return None
        return _check(
            snap, session, f"{self.path}:{session}",
            set_aside=lambda: _aside(".refused"),
        )

    def close(self) -> None:
        with self._lock:
            try:
                self._conn.close()
            except Exception:
                pass


class MutationJournal:
    """Append-only mutation log between snapshot ticks.

    ray: the reference's GCS has no snapshot window at all — every table
    mutation goes through the store client (redis_store_client.h) before
    the RPC is acked.  Ours keeps the cheap snapshot document but closes
    the between-tick loss window with this journal: every actor
    register/restart/death, named binding, job transition, and inline-
    result lineage record appends one entry; restore replays the entries
    over the snapshot.  The journal is RESET after every successful
    snapshot save (the snapshot now contains everything the journal did —
    compaction), so it stays tick-sized.

    Record format (after a pickled header stamping session + version):

        u32 length | u32 crc32(blob) | blob=pickle(entry)

    Appends GROUP-COMMIT: entries staged within a flush window
    (RAY_TPU_JOURNAL_FLUSH_US linger / RAY_TPU_JOURNAL_BATCH_BYTES size)
    land as one buffered write, order preserved — the per-mutation
    write+flush pair was a measured per-task syscall tax on the hot
    completion path (every inline-result lineage entry paid it).

    A torn tail (head SIGKILLed mid-append) is TOLERATED: replay stops at
    the first short/corrupt record and truncates the file there — every
    complete record before the tear still replays.  A foreign session or
    a version-mismatched header refuses replay loudly, exactly like the
    snapshot document (the file is set aside, never overwritten)."""

    HEADER_VERSION = SNAPSHOT_VERSION

    def __init__(self, path: str, session: str):
        import threading

        self.path = path
        self.session = session
        self._lock = threading.Lock()
        self._f = None
        self._entries_since_fsync = 0
        # GROUP COMMIT (the BatchingConn size/linger discipline applied to
        # the journal file): crc-framed entry records accumulate in
        # _pending and flush as ONE buffered write when the batch crosses
        # gcs_journal_batch_bytes, when the linger
        # (gcs_journal_flush_us) expires, or explicitly (snapshot fold,
        # replay, close).  Entry ORDER is append order — records are
        # framed at append time under the lock and the flush writes the
        # joined run, so replay sees exactly the sequence the mutators
        # produced.  Loss window: a SIGKILL can eat at most the unflushed
        # linger window — the same bounded-loss contract wire batching
        # has, and the reconciliation handshake covers actor records
        # regardless.
        self._pending: list = []
        self._pending_bytes = 0
        self._flush_event = threading.Event()
        self._flusher = None
        self._closed = False
        # Physical-write/entry/fsync counters (the perf surface:
        # journal_appends_per_op measures WRITES — group commit drops it
        # while entries/op stays 1:1 with mutations).
        self.entries = 0
        self.writes = 0
        self.fsyncs = 0

    # -- writing -------------------------------------------------------------

    def _open_locked(self):
        if self._f is None:
            self._f = open(self.path, "ab")
        return self._f

    def _frame(self, entry) -> bytes:
        import struct
        import zlib

        blob = pickle.dumps(entry)
        return struct.pack("<II", len(blob), zlib.crc32(blob)) + blob

    def append(self, entry) -> bool:
        """Stage one mutation for the next group commit; True when this
        call itself issued an fsync (size-triggered inline flush under an
        fsync policy).  With gcs_journal_flush_us=0 this degrades to the
        pre-batching write-per-append behavior.  Raises on I/O failure —
        callers treat the journal as best-effort (the next snapshot tick
        re-captures the full tables)."""
        if faults.ENABLED:
            # crash -> head death mid-append (the torn tail replay must
            # tolerate); drop -> this mutation is silently lost (the
            # reconciliation handshake must still recover the actor);
            # error -> append fails, caller presses on un-durable.
            if faults.point("gcs.journal_append", key=_entry_kind(entry)) == "drop":
                return False
        from ray_tpu._private import config as _config

        rec = self._frame(entry)
        linger_us = _config.get("gcs_journal_flush_us")
        batch_bytes = _config.get("gcs_journal_batch_bytes")
        with self._lock:
            self._pending.append(rec)
            self._pending_bytes += len(rec)
            self.entries += 1
            if linger_us <= 0 or self._pending_bytes >= batch_bytes:
                return self._flush_locked()
        # Arm the linger sweep (one daemon thread per journal, started
        # lazily on the first batched append).
        self._ensure_flusher(linger_us / 1e6)
        self._flush_event.set()
        return False

    def _ensure_flusher(self, linger_s: float) -> None:
        if self._flusher is not None:
            return
        import threading

        def _loop():
            while not self._closed:
                self._flush_event.wait()
                self._flush_event.clear()
                if self._closed:
                    return
                if linger_s > 0:
                    import time as _time

                    _time.sleep(linger_s)
                try:
                    self.flush()
                except Exception:
                    pass  # best-effort; next append or snapshot retries

        t = threading.Thread(
            target=_loop, daemon=True, name="raytpu-journal-flush"
        )
        self._flusher = t
        t.start()

    def flush(self) -> bool:
        """Write the pending batch NOW (snapshot fold, replay, close, and
        the linger sweep all land here).  True if an fsync was issued."""
        with self._lock:
            return self._flush_locked()

    def _flush_locked(self) -> bool:
        if not self._pending:
            return False
        from ray_tpu._private import config as _config

        batch, n = self._pending, len(self._pending)
        self._pending = []
        self._pending_bytes = 0
        fsync_every = _config.get("gcs_journal_fsync")
        synced = False
        f = self._open_locked()
        if f.tell() == 0:
            hdr = pickle.dumps(
                {"session": self.session, "journal_version": self.HEADER_VERSION}
            )
            f.write(self._frame_header(hdr))
        f.write(b"".join(batch) if n > 1 else batch[0])
        # flush() moves the bytes into the page cache: a SIGKILLed
        # head loses nothing past this point (fsync only defends
        # against host death).
        f.flush()
        self.writes += 1
        if fsync_every > 0:
            self._entries_since_fsync += n
            if self._entries_since_fsync >= fsync_every:
                os.fsync(f.fileno())
                self._entries_since_fsync = 0
                self.fsyncs += 1
                synced = True
        return synced

    @staticmethod
    def _frame_header(hdr: bytes) -> bytes:
        import struct
        import zlib

        return struct.pack("<II", len(hdr), zlib.crc32(hdr)) + hdr

    def size_bytes(self) -> int:
        with self._lock:
            pending = self._pending_bytes
            if self._f is not None:
                return self._f.tell() + pending
        try:
            return os.path.getsize(self.path) + pending
        except OSError:
            return pending

    def reset(self) -> None:
        """Compaction point: the snapshot just captured everything this
        journal recorded — start a fresh (empty) journal.  Staged-but-
        unflushed entries are captured by that same snapshot (it reads
        the live tables), so the pending batch drops with the file."""
        with self._lock:
            self._pending = []
            self._pending_bytes = 0
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
            try:
                os.unlink(self.path)
            except OSError:
                pass
            self._entries_since_fsync = 0

    def close(self) -> None:
        self._closed = True
        self._flush_event.set()  # release the flusher to exit
        try:
            self.flush()
        except Exception:
            pass
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    # -- replay --------------------------------------------------------------

    def _read_records(self, data: bytes):
        """(entries, good_offset): decode until EOF or the first torn/
        corrupt record."""
        import struct
        import zlib

        entries = []
        off = 0
        while off + 8 <= len(data):
            length, crc = struct.unpack_from("<II", data, off)
            start = off + 8
            end = start + length
            if end > len(data):
                break  # torn tail: length header written, body incomplete
            blob = data[start:end]
            if zlib.crc32(blob) != crc:
                break  # torn/corrupt record: stop here, keep the prefix
            try:
                entries.append(pickle.loads(blob))
            except Exception:
                break
            off = end
        return entries, off

    def replay(self):
        """Entries recorded since the last snapshot (possibly many ticks
        ago if saves kept failing), or [] when there is nothing to replay
        / the journal must not replay (foreign session, version skew)."""
        if faults.ENABLED:
            faults.point("gcs.journal_replay", key=self.session)
        try:
            # Same-process read-back (tests, diagnostics): the pending
            # batch must be on disk first.  A restarted head replays a
            # fresh object, where this is a no-op.
            self.flush()
        except Exception:
            pass
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return []
        except OSError as e:
            _corrupt_note(self.path, e)
            return []
        entries, good = self._read_records(data)
        if good < len(data):
            # Torn tail (head died mid-append): truncate to the last
            # complete record so the NEXT incarnation's appends don't land
            # after garbage.
            print(
                f"[ray_tpu] journal at {self.path}: torn tail at byte "
                f"{good}/{len(data)} — recovered {max(len(entries) - 1, 0)} "
                "complete record(s), truncating the tear",
                file=sys.stderr,
                flush=True,
            )
            try:
                with open(self.path, "r+b") as f:
                    f.truncate(good)
            except OSError:
                pass
        if not entries:
            return []
        header, entries = entries[0], entries[1:]
        if not isinstance(header, dict) or header.get("journal_version") != self.HEADER_VERSION:
            ver = header.get("journal_version") if isinstance(header, dict) else None
            print(
                f"[ray_tpu] REFUSING journal replay from {self.path}: "
                f"version {ver!r} != supported {self.HEADER_VERSION} — the "
                "journaled mutations were NOT replayed (kept aside for a "
                "matching-version binary)",
                file=sys.stderr,
                flush=True,
            )
            try:
                os.replace(self.path, self.path + ".refused")
            except OSError:
                pass
            return []
        if header.get("session") != self.session:
            return []  # a foreign session's mutations must never replay
        return entries


def _entry_kind(entry) -> str:
    if isinstance(entry, tuple) and entry and isinstance(entry[0], str):
        return entry[0]
    return type(entry).__name__


def make_mutation_journal(snapshot_path: str, session: str) -> MutationJournal:
    """The journal rides next to the snapshot document regardless of the
    snapshot backend (sqlite's transactional saves don't help the BETWEEN-
    tick window; the file journal is one implementation for both)."""
    return MutationJournal(snapshot_path + ".journal", session)


def make_snapshot_storage(path: str) -> SnapshotStorage:
    """Backend per the gcs_storage_backend knob ('file' | 'sqlite')."""
    from ray_tpu._private import config as _config

    backend = _config.get("gcs_storage_backend")
    if backend == "sqlite":
        return SqliteSnapshotStorage(
            path if path.endswith(".db") else path + ".db"
        )
    if backend != "file":
        raise ValueError(
            f"unknown gcs_storage_backend {backend!r} (want 'file' or 'sqlite')"
        )
    return FileSnapshotStorage(path)
