"""Pluggable control-plane snapshot storage.

ray: src/ray/gcs/store_client/ — the reference's GCS persists its tables
through a StoreClient interface with in-memory and Redis backends
(in_memory_store_client.h, redis_store_client.h).  Ours snapshots the
metadata tables as one document per tick; this module makes WHERE that
document lives pluggable:

  * FileSnapshotStorage  — atomic tmp+rename single file (the default;
    zero dependencies, good for one-host clusters and tests);
  * SqliteSnapshotStorage — a WAL-mode sqlite database (crash-safe
    journaled writes, multiple sessions per db file, the shape an external
    durable store plugs into — the Redis-FT analogue without a Redis
    dependency in this image).

Selected by the gcs_storage_backend config knob (RAY_TPU_GCS_STORAGE_BACKEND).
"""

from __future__ import annotations

import os
import pickle
import sys
from typing import Any, Dict, Optional

from ray_tpu._private import faults

# Version of the snapshot DOCUMENT (not the wire protocol): bumped when
# the snapshot's shape changes incompatibly.  Restore-time mismatch is
# LOUD — a silent clean boot on a version bump would quietly drop
# detached actors / KV / lineage (the round-4 verdict's "pickle can
# silently fail restore" finding); the wire got versioning in round 4,
# this is the storage twin (ray: proto-versioned GCS tables).
SNAPSHOT_VERSION = 1


def _stamp(snap: Dict[str, Any]) -> Dict[str, Any]:
    snap["snapshot_version"] = SNAPSHOT_VERSION
    return snap


def _check(
    snap: Dict[str, Any], session: str, origin: str, set_aside=None
) -> Optional[Dict[str, Any]]:
    """Validate a loaded document; None (with a loud stderr note) when it
    must not replay.  `set_aside()` preserves a version-refused document
    out of the save path — without it, the next snapshot tick would
    overwrite the very state the refusal promised not to lose."""
    ver = snap.get("snapshot_version")
    if ver != SNAPSHOT_VERSION:
        print(
            f"[ray_tpu] REFUSING snapshot restore from {origin}: document "
            f"version {ver!r} != supported {SNAPSHOT_VERSION} — starting "
            "clean; the prior control-plane state (detached actors, KV) "
            f"was NOT restored (kept aside for a matching-version binary)",
            file=sys.stderr,
            flush=True,
        )
        if set_aside is not None:
            try:
                set_aside()
            except Exception:
                pass
        return None
    # Session-scoped storage; a foreign session's snapshot must never
    # replay (the caller also re-checks).
    if snap.get("session") != session:
        return None
    return snap


def _corrupt_note(origin: str, err: Exception) -> None:
    print(
        f"[ray_tpu] snapshot at {origin} is unreadable ({type(err).__name__}: "
        f"{err}) — starting clean; prior control-plane state NOT restored",
        file=sys.stderr,
        flush=True,
    )


class SnapshotStorage:
    """Interface: persist/load one session's snapshot document."""

    def save(self, session: str, snap: Dict[str, Any]) -> None:
        raise NotImplementedError

    def load(self, session: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileSnapshotStorage(SnapshotStorage):
    """One pickle file, atomically replaced per tick."""

    def __init__(self, path: str):
        self.path = path

    def save(self, session: str, snap: Dict[str, Any]) -> None:
        if faults.ENABLED:
            # error -> this tick is skipped (the snapshot loop is
            # best-effort); crash -> head death mid-persist, which the
            # atomic tmp+rename below must survive.
            faults.point("gcs.save", key=session)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(_stamp(snap), f)
        os.replace(tmp, self.path)

    def load(self, session: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path, "rb") as f:
                snap = pickle.load(f)
        except FileNotFoundError:
            return None  # genuinely clean boot
        except Exception as e:  # noqa: BLE001 — unreadable ≠ absent
            # Unreadable is NOT "absent": say so, and keep the evidence
            # aside instead of overwriting it on the next save tick.
            _corrupt_note(self.path, e)
            try:
                os.replace(self.path, self.path + ".corrupt")
            except OSError:
                pass
            return None
        return _check(
            snap, session, self.path,
            set_aside=lambda: os.replace(self.path, self.path + ".refused"),
        )


class SqliteSnapshotStorage(SnapshotStorage):
    """WAL-journaled sqlite table keyed by session name.

    One db can hold many sessions' snapshots; writes are transactional, so
    a crash mid-save leaves the previous snapshot intact (the property the
    reference gets from Redis persistence)."""

    def __init__(self, path: str):
        import sqlite3

        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS snapshots ("
            "session TEXT PRIMARY KEY, snap BLOB, updated REAL)"
        )
        self._conn.commit()
        import threading

        self._lock = threading.Lock()

    def save(self, session: str, snap: Dict[str, Any]) -> None:
        import time

        if faults.ENABLED:
            faults.point("gcs.save", key=session)
        blob = pickle.dumps(_stamp(snap))
        with self._lock:
            self._conn.execute(
                "INSERT INTO snapshots (session, snap, updated) "
                "VALUES (?, ?, ?) ON CONFLICT(session) DO UPDATE SET "
                "snap=excluded.snap, updated=excluded.updated",
                (session, blob, time.time()),
            )
            self._conn.commit()

    def load(self, session: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            row = self._conn.execute(
                "SELECT snap FROM snapshots WHERE session=?", (session,)
            ).fetchone()
        if row is None:
            return None  # genuinely clean boot
        def _aside(suffix: str) -> None:
            with self._lock:
                self._conn.execute(
                    "UPDATE snapshots SET session=? WHERE session=?",
                    (session + suffix, session),
                )
                self._conn.commit()

        try:
            snap = pickle.loads(row[0])
        except Exception as e:  # noqa: BLE001 — unreadable ≠ absent
            _corrupt_note(f"{self.path}:{session}", e)
            try:
                _aside(".corrupt")  # next save tick must not destroy it
            except Exception:
                pass
            return None
        return _check(
            snap, session, f"{self.path}:{session}",
            set_aside=lambda: _aside(".refused"),
        )

    def close(self) -> None:
        with self._lock:
            try:
                self._conn.close()
            except Exception:
                pass


def make_snapshot_storage(path: str) -> SnapshotStorage:
    """Backend per the gcs_storage_backend knob ('file' | 'sqlite')."""
    from ray_tpu._private import config as _config

    backend = _config.get("gcs_storage_backend")
    if backend == "sqlite":
        return SqliteSnapshotStorage(
            path if path.endswith(".db") else path + ".db"
        )
    if backend != "file":
        raise ValueError(
            f"unknown gcs_storage_backend {backend!r} (want 'file' or 'sqlite')"
        )
    return FileSnapshotStorage(path)
