"""Pluggable control-plane snapshot storage.

ray: src/ray/gcs/store_client/ — the reference's GCS persists its tables
through a StoreClient interface with in-memory and Redis backends
(in_memory_store_client.h, redis_store_client.h).  Ours snapshots the
metadata tables as one document per tick; this module makes WHERE that
document lives pluggable:

  * FileSnapshotStorage  — atomic tmp+rename single file (the default;
    zero dependencies, good for one-host clusters and tests);
  * SqliteSnapshotStorage — a WAL-mode sqlite database (crash-safe
    journaled writes, multiple sessions per db file, the shape an external
    durable store plugs into — the Redis-FT analogue without a Redis
    dependency in this image).

Selected by the gcs_storage_backend config knob (RAY_TPU_GCS_STORAGE_BACKEND).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional


class SnapshotStorage:
    """Interface: persist/load one session's snapshot document."""

    def save(self, session: str, snap: Dict[str, Any]) -> None:
        raise NotImplementedError

    def load(self, session: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileSnapshotStorage(SnapshotStorage):
    """One pickle file, atomically replaced per tick."""

    def __init__(self, path: str):
        self.path = path

    def save(self, session: str, snap: Dict[str, Any]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(snap, f)
        os.replace(tmp, self.path)

    def load(self, session: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path, "rb") as f:
                snap = pickle.load(f)
        except (OSError, EOFError, pickle.UnpicklingError):
            return None
        # The file is session-scoped by its directory; a foreign session's
        # snapshot must never replay (the caller also re-checks).
        if snap.get("session") != session:
            return None
        return snap


class SqliteSnapshotStorage(SnapshotStorage):
    """WAL-journaled sqlite table keyed by session name.

    One db can hold many sessions' snapshots; writes are transactional, so
    a crash mid-save leaves the previous snapshot intact (the property the
    reference gets from Redis persistence)."""

    def __init__(self, path: str):
        import sqlite3

        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS snapshots ("
            "session TEXT PRIMARY KEY, snap BLOB, updated REAL)"
        )
        self._conn.commit()
        import threading

        self._lock = threading.Lock()

    def save(self, session: str, snap: Dict[str, Any]) -> None:
        import time

        blob = pickle.dumps(snap)
        with self._lock:
            self._conn.execute(
                "INSERT INTO snapshots (session, snap, updated) "
                "VALUES (?, ?, ?) ON CONFLICT(session) DO UPDATE SET "
                "snap=excluded.snap, updated=excluded.updated",
                (session, blob, time.time()),
            )
            self._conn.commit()

    def load(self, session: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            row = self._conn.execute(
                "SELECT snap FROM snapshots WHERE session=?", (session,)
            ).fetchone()
        if row is None:
            return None
        try:
            snap = pickle.loads(row[0])
        except (pickle.UnpicklingError, EOFError):
            return None
        if snap.get("session") != session:
            return None
        return snap

    def close(self) -> None:
        with self._lock:
            try:
                self._conn.close()
            except Exception:
                pass


def make_snapshot_storage(path: str) -> SnapshotStorage:
    """Backend per the gcs_storage_backend knob ('file' | 'sqlite')."""
    from ray_tpu._private import config as _config

    backend = _config.get("gcs_storage_backend")
    if backend == "sqlite":
        return SqliteSnapshotStorage(
            path if path.endswith(".db") else path + ".db"
        )
    if backend != "file":
        raise ValueError(
            f"unknown gcs_storage_backend {backend!r} (want 'file' or 'sqlite')"
        )
    return FileSnapshotStorage(path)
