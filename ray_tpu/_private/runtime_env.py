"""Runtime environments: per-task/actor working_dir + py_modules + env_vars.

ray: python/ray/_private/runtime_env/{working_dir,py_modules,packaging,
uri_cache}.py — directories are zipped, content-addressed as pkg:// URIs,
shipped through the cluster KV store, and extracted into a per-host cache
that workers add to sys.path / chdir into.  env_vars flow through the
worker spawn env (runtime.py) as before.
"""

from __future__ import annotations

import hashlib
import io
import os
import tempfile
import threading
import zipfile
from typing import Any, Dict, List, Optional, Tuple

_EXCLUDES = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_PKG_BYTES = 256 * 1024 * 1024  # ray: working_dir size cap spirit

_pkg_cache_lock = threading.Lock()
# (session, fingerprint) -> uri ONLY: retaining the zip payload would leak
# every edited version of the dir in driver memory (the bytes are needed
# exactly once per SESSION for the kv upload — the KV store dies with its
# Runtime, so a new session must re-upload even for an unchanged dir).
_fingerprint_to_uri: Dict[Tuple, str] = {}


def _dir_fingerprint(path: str) -> Tuple:
    """Cheap change detector: (relpath, mtime, size) of every file.  The
    directory's own mtime is NOT enough — editing a file's contents leaves
    it unchanged, which would ship stale code."""
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in _EXCLUDES]
        for fname in sorted(files):
            full = os.path.join(root, fname)
            st = os.stat(full)
            entries.append((os.path.relpath(full, path), st.st_mtime, st.st_size))
    return (path, tuple(entries))


def package_dir(path: str, session: Optional[str] = None) -> Tuple[str, Optional[bytes]]:
    """Zip a directory into a content-addressed pkg:// URI.

    Returns (uri, zip_bytes); zip_bytes is None on a cache hit (the payload
    was already uploaded to this session — nothing retains it)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory not found: {path}")
    key = (session, _dir_fingerprint(path))
    with _pkg_cache_lock:
        hit = _fingerprint_to_uri.get(key)
        if hit is not None:
            return hit, None
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _EXCLUDES]
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                total += os.path.getsize(full)
                if total > _MAX_PKG_BYTES:
                    raise ValueError(
                        f"runtime_env dir {path} exceeds {_MAX_PKG_BYTES} bytes"
                    )
                z.write(full, rel)
    data = buf.getvalue()
    uri = "pkg://" + hashlib.sha1(data).hexdigest()[:20]
    with _pkg_cache_lock:
        _fingerprint_to_uri[key] = uri
    return uri, data


_SUPPORTED_KEYS = frozenset(
    {"env_vars", "working_dir", "py_modules", "pip", "_resolved", "_orig"}
)


def validate_runtime_env(renv: Optional[Dict[str, Any]]) -> None:
    """Fail UNKNOWN/unsupported runtime_env fields at submit time.

    conda/container (ray: _private/runtime_env/{conda,container}.py) need
    a conda toolchain / container runtime this framework doesn't manage —
    a clear driver-side error beats a worker-boot mystery; typos in
    supported keys surface the same way."""
    if not renv:
        return
    unknown = set(renv) - _SUPPORTED_KEYS
    if unknown:
        from ray_tpu.exceptions import RuntimeEnvSetupError

        hints = {
            "conda": "use runtime_env={'pip': [...]} (per-host target installs)",
            "container": "run the node daemon inside your container instead",
        }
        notes = "; ".join(f"{k}: {hints[k]}" for k in sorted(unknown) if k in hints)
        raise RuntimeEnvSetupError(
            f"unsupported runtime_env keys {sorted(unknown)} "
            f"(supported: {sorted(k for k in _SUPPORTED_KEYS if not k.startswith('_'))})"
            + (f" — {notes}" if notes else "")
        )


def resolve_runtime_env(
    renv: Optional[Dict[str, Any]], kv_put, session: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """Driver-side: package local dirs → URIs, upload once PER SESSION to
    the KV store.  Returns the resolved env shipped to workers."""
    if not renv:
        return renv
    out = dict(renv)
    wd = out.get("working_dir")
    if wd and not str(wd).startswith("pkg://"):
        uri, data = package_dir(wd, session)
        if data is not None:
            kv_put(uri, data)
        out["working_dir"] = uri
    mods = out.get("py_modules")
    if mods:
        uris = []
        for m in mods:
            if str(m).startswith("pkg://"):
                uris.append(m)
            else:
                uri, data = package_dir(m, session)
                if data is not None:
                    kv_put(uri, data)
                uris.append(uri)
        out["py_modules"] = uris
    return out


def worker_env_entries(renv: Optional[Dict[str, Any]]) -> Dict[str, str]:
    """The RAY_TPU_* env entries a worker spawn needs for its runtime env
    (single source for the driver-local and node-daemon spawn paths)."""
    import json

    renv = renv or {}
    out = {"RAY_TPU_ENV_VARS": json.dumps(renv.get("env_vars") or {})}
    if renv.get("working_dir") or renv.get("py_modules") or renv.get("pip"):
        out["RAY_TPU_RUNTIME_ENV"] = json.dumps(
            {k: renv.get(k) for k in ("working_dir", "py_modules", "pip")}
        )
    return out


def _extract_cache_dir() -> str:
    return os.environ.get(
        "RAY_TPU_PKG_CACHE",
        os.path.join(tempfile.gettempdir(), "raytpu-pkg-cache"),
    )


def fetch_and_extract(uri: str, kv_get) -> str:
    """Worker-side: materialize a pkg:// URI into the host cache (idempotent
    across workers — content-addressed dir + atomic rename)."""
    assert uri.startswith("pkg://")
    dest = os.path.join(_extract_cache_dir(), uri[len("pkg://") :])
    if os.path.isdir(dest):
        return dest
    data = kv_get(uri)
    if data is None:
        raise ValueError(f"runtime_env package {uri} missing from KV store")
    tmp = dest + f".tmp-{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        z.extractall(tmp)
    try:
        os.replace(tmp, dest)
    except OSError:
        # another worker won the race; use theirs
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def pip_env_dir(specs: List[str]) -> str:
    """Worker-host-side pip environment (ray: _private/runtime_env/pip.py,
    installed there by the per-node agent; here by the first worker that
    needs it — content-hashed and shared by every later worker on the
    host).

    `pip install --target` into a per-spec-list cache dir; local
    wheels/dirs work fully offline, index installs need egress (a clear
    error either way, never a silent no-op).  Concurrent first installs
    race benignly: both build tmp dirs, one atomic-renames, losers adopt
    the winner's.
    """
    import shutil
    import subprocess
    import sys

    key = hashlib.sha256("\x00".join(sorted(specs)).encode()).hexdigest()[:16]
    dest = os.path.join(_extract_cache_dir(), "pip", key)
    if os.path.isdir(dest):
        return dest
    tmp = dest + f".tmp-{os.getpid()}"
    cmd = [
        sys.executable, "-m", "pip", "install", "--target", tmp,
        # --no-build-isolation: build local source dirs against the
        # ambient setuptools instead of fetching a build backend — keeps
        # local-path installs fully offline.
        "--no-input", "--disable-pip-version-check", "--quiet",
        "--no-build-isolation", *specs,
    ]
    from ray_tpu.exceptions import RuntimeEnvSetupError

    try:
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeEnvSetupError(
            f"pip runtime_env install failed for {specs}: timed out after 600s"
        )
    if out.returncode != 0:
        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeEnvSetupError(
            f"pip runtime_env install failed for {specs}: {out.stderr[-800:]}"
        )
    try:
        os.replace(tmp, dest)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)  # another worker won
    return dest


def apply_worker_runtime_env(renv: Optional[Dict[str, Any]], kv_get) -> None:
    """Worker-side: chdir into working_dir, put py_modules + working_dir +
    the pip env on sys.path (ray: workers import user code from the
    extracted URIs / the agent-built pip env)."""
    if not renv:
        return
    import sys

    pip_specs = renv.get("pip") or []
    if pip_specs:
        path = pip_env_dir([str(s) for s in pip_specs])
        if path not in sys.path:
            sys.path.insert(0, path)
    for uri in renv.get("py_modules") or []:
        path = fetch_and_extract(uri, kv_get)
        if path not in sys.path:
            sys.path.insert(0, path)
    wd = renv.get("working_dir")
    if wd:
        path = fetch_and_extract(wd, kv_get) if str(wd).startswith("pkg://") else wd
        os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)
