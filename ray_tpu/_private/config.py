"""Runtime config knobs: one table, env-var overridable.

ray: src/ray/common/ray_config_def.h (the RAY_CONFIG X-macro table — every
runtime knob declared once, overridable via RAY_<name> env vars) +
python/ray/_private/ray_constants.py.  Same shape here: each knob is a row
with a default and docstring; `RAY_TPU_<NAME>` env vars override at first
access; `_system_config` overrides at init beat both.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict

_DEFS: Dict[str, tuple] = {
    # name: (default, type, doc)
    "scheduler_spread_threshold": (
        0.5, float,
        "hybrid policy: head-node utilization above which tasks spill to "
        "the least-utilized remote node (ray: RAY_scheduler_spread_threshold)",
    ),
    "max_direct_call_object_size": (
        100 * 1024, int,
        "results >= this many bytes go to the shm store; smaller inline "
        "over the control conn (ray: max_direct_call_object_size)",
    ),
    "object_store_memory": (
        0, int,
        "shm store capacity in bytes; 0 = 30% of the shm filesystem's free "
        "space at init (ray: object_store_memory)",
    ),
    "lineage_max_entries": (
        10000, int,
        "max producer TaskSpecs retained for object reconstruction",
    ),
    "lineage_max_bytes": (
        64 * 1024 * 1024, int,
        "max bytes of retained args blobs in the lineage table "
        "(ray: max_lineage_bytes spirit, task_manager.h:97)",
    ),
    "task_events_max": (
        2000, int,
        "ring-buffer size of the finished-task event sink "
        "(ray: task_events_max_num_task_in_gcs)",
    ),
    "worker_prestart_count": (
        8, int,
        "warm worker-pool size prestarted at init (capped by node CPUs; "
        "ray: worker pool prestart)",
    ),
    "use_zygote": (
        1, int,
        "1 = spawn local workers by forking the pre-warmed zygote "
        "(~2ms); 0 = exec a fresh interpreter per worker (zygote.py)",
    ),
    "worker_handshake_timeout_s": (
        60.0, float,
        "a spawned worker that hasn't connected within this window dies "
        "via its own watchdog",
    ),
    "spill_storage_uri": (
        "", str,
        "external spill target URI (file:// native; s3://gs:// via fsspec "
        "when installed); empty = session-local spill directory "
        "(ray: external_storage.py:185)",
    ),
    "native_store": (
        1, int,
        "1 = use the C++ shm arena when it builds; 0 = file-per-object",
    ),
    "bind_host": (
        "127.0.0.1", str,
        "driver listener bind address; 0.0.0.0 exposes it to node daemons "
        "on other machines",
    ),
    "object_transfer_chunk_bytes": (
        8 * 1024 * 1024, int,
        "chunk size for cross-node object pulls "
        "(ray: object_manager_default_chunk_size)",
    ),
    "gcs_storage_backend": (
        "file", str,
        "control-plane snapshot backend: 'file' (atomic single file) or "
        "'sqlite' (WAL-journaled, crash-safe) "
        "(ray: gcs store_client in-memory vs redis backends)",
    ),
    "gcs_journal": (
        1, int,
        "1 = append-only mutation journal between snapshot ticks (actor "
        "register/restart/death, named bindings, job transitions, inline-"
        "result lineage), replayed over the snapshot at head restart; "
        "0 = snapshot-only durability (up to one tick of mutations lost) "
        "(ray: the GCS writes each table mutation through its store "
        "client instead of snapshotting)",
    ),
    "gcs_journal_fsync": (
        0, int,
        "journal append durability: 0 = write+flush only (survives "
        "process SIGKILL via the page cache — the chaos-soak envelope), "
        "1 = fsync every append (survives host power loss), N>1 = fsync "
        "every N-th append (bounded-loss middle ground)",
    ),
    "gcs_journal_compact_bytes": (
        4 * 1024 * 1024, int,
        "journal size that forces an immediate snapshot (which folds the "
        "journal in and resets it) instead of waiting for the next tick",
    ),
    "snapshot_inflight_max_blob_bytes": (
        256 * 1024, int,
        "in-flight tasks with args blobs over this size are not persisted "
        "for head-restart re-drive (their argument objects would not "
        "survive the head's store anyway)",
    ),
    "snapshot_inflight_max_tasks": (
        10000, int,
        "cap on in-flight task specs persisted per snapshot tick",
    ),
    "locality_min_bytes": (
        1024 * 1024, int,
        "dependency-locality scoring floor: tasks whose LARGEST per-node "
        "local dep footprint is under this many bytes schedule by load "
        "alone (pulling tiny args costs less than imbalance)",
    ),
    "serve_proxy_max_connections": (
        2048, int,
        "max concurrent HTTP connections one serve proxy holds open; "
        "connections beyond the bound are refused at accept "
        "(ray: uvicorn's backlog/limit-concurrency role)",
    ),
    "serve_proxy_threads": (
        32, int,
        "executor threads one serve proxy uses to resolve replica "
        "responses; bounds active requests while idle keep-alive "
        "connections cost only a coroutine",
    ),
    "object_transfer_max_concurrency": (
        8, int,
        "max concurrent outbound transfers an object server runs; excess "
        "fetches queue (ray: object_manager_max_bytes_in_flight spirit)",
    ),
    "object_transfer_timeout_s": (
        120.0, float,
        "bound on every blocking step of a cross-node object pull "
        "(connect, header, each chunk) — a wedged server fails the fetch "
        "instead of hanging the get (ray: pull retry timer spirit)",
    ),
    "relay_pipeline": (
        1, int,
        "1 = broadcast pulls get a pipelined transfer plan: in-flight "
        "pullers re-serve landed chunks mid-transfer (chain/tree "
        "broadcast, ray: push_manager.h chunk pipelining); 0 = classic "
        "staggered whole-object rounds (grants capped at sealed copies)",
    ),
    "relay_fanout": (
        2, int,
        "max concurrent downstream pullers one feed (sealed source OR "
        "in-flight relay) serves in a transfer plan; each admitted "
        "puller immediately becomes a feed itself, so admission capacity "
        "grows with the tree instead of with completed rounds",
    ),
    "relay_stall_timeout_s": (
        10.0, float,
        "relay liveness bound, both sides: a relay server whose upstream "
        "watermark stops advancing closes the conn after this long, and "
        "a receiver whose relay feed goes silent fails the fetch and "
        "falls back to a sealed source (re-plan, not wedge)",
    ),
    "node_ip": (
        "127.0.0.1", str,
        "address this node's object server advertises to other nodes "
        "(set RAY_TPU_NODE_IP per host in real multi-host deployments)",
    ),
    "reconnect_window_s": (
        0.0, float,
        "how long daemons/workers retry connecting after losing the head "
        "conn before giving up and exiting; 0 = die on EOF (classic mode). "
        "The standalone head sets this for its cluster so a head restart "
        "is survivable (ray: gcs_rpc_server_reconnect_timeout_s)",
    ),
    "log_to_driver": (
        1, int,
        "1 = echo worker stdout/stderr lines (prefixed) to the driver/head "
        "process stdout as they arrive; 0 = files + ring buffers only "
        "(ray: ray.init(log_to_driver=...))",
    ),
    "worker_log_ring_lines": (
        2000, int,
        "per-worker ring buffer of recent log lines kept for the logs "
        "CLI / dashboard endpoint",
    ),
    "health_check_period_ms": (
        1000, int,
        "how often node daemons send liveness heartbeats to the head "
        "(ray: health_check_period_ms, gcs_health_check_manager.h:39)",
    ),
    "health_check_timeout_ms": (
        10000, int,
        "no heartbeat for this long => the node is declared dead even "
        "with its TCP conn still open (hung daemon / half-open conn); "
        "0 disables timeout-based death (EOF only)",
    ),
    "memory_monitor_refresh_ms": (
        250, int,
        "how often each node daemon checks memory pressure; 0 disables "
        "the OOM monitor (ray: memory_monitor_refresh_ms)",
    ),
    "memory_usage_threshold": (
        0.95, float,
        "usage fraction above which the daemon kills a worker "
        "(ray: memory_usage_threshold)",
    ),
    "memory_limit_bytes": (
        0, int,
        "per-node worker-group RSS budget; 0 = account whole-system "
        "memory from /proc/meminfo instead (the deployment default)",
    ),
    "task_oom_retries": (
        3, int,
        "extra retry budget for tasks whose worker was OOM-killed, "
        "separate from max_retries (ray: task_oom_retries)",
    ),
    "oom_worker_killing_policy": (
        "largest", str,
        "victim choice under memory pressure: 'largest' RSS (finds the "
        "actual hog — prestarted idle workers are never bigger) or "
        "'newest' spawned (ray: worker_killing_policy.h)",
    ),
    "wire_batch_bytes": (
        64 * 1024, int,
        "control-plane frame coalescing: pending bytes at which a "
        "BatchingConn flushes (one physical write per batch); 0 disables "
        "batching entirely (every frame is its own write — the unbatched "
        "comparison baseline; ray: gRPC stream buffering plays this role)",
    ),
    "wire_guard": (
        1, int,
        "1 = bounds-check native frame bodies before marshal.loads "
        "(every declared string length / container count must fit the "
        "bytes present, cumulative allocation capped at O(body)) — a "
        "corrupted or hostile 11-byte body can otherwise make the "
        "decoder pre-allocate gigabytes; costs a few µs per native "
        "frame; 0 trusts the fabric and decodes unguarded",
    ),
    "wire_flush_us": (
        200, int,
        "linger bound on a pending control-frame batch: the background "
        "flusher sweeps dirty conns after this many microseconds, so "
        "fire-and-forget frames never wait longer than ~this (blocking "
        "paths flush explicitly and never wait at all)",
    ),
    "wire_native": (
        1, int,
        "1 = encode the hot control-frame kinds (task push, done, refop, "
        "metrics/refs/prof pushes, shard forwards) with the struct-framed "
        "native codec (wire_native.py: marshal data tuples, no pickle, "
        "~14x cheaper per TaskSpec); 0 = pickle every frame (the v2 "
        "behavior).  Negotiated by the protocol-version fence; kinds "
        "without a native codec fall back to pickle per frame either way",
    ),
    "lease_pipeline_depth": (
        0, int,
        "caller-side direct transport: unacked tasks one worker lease "
        "pipelines before another worker is leased; 0 = auto "
        "(max(4, 64/cpus) — deep pipelining onto few executors wins on "
        "small hosts, fan-out wins on many-core; resolved at process "
        "start)",
    ),
    "lease_max_per_key": (
        0, int,
        "caller-side direct transport: max worker leases one scheduling "
        "key holds; 0 = auto (min(8, cpus), floor 1; resolved at process "
        "start)",
    ),
    "task_lease_idle_s": (
        2.0, float,
        "head-side lease reuse: how long a worker leased to a scheduling "
        "key (fn + resource shape + strategy) stays bound after its last "
        "same-key task before the lease is revoked and the worker "
        "returns to the shared pool (ray: "
        "worker_lease_timeout_milliseconds + direct_task_transport.h:40 "
        "lease reuse keyed by SchedulingKey)",
    ),
    "gcs_journal_flush_us": (
        500, int,
        "journal group-commit linger: mutation entries accumulate for up "
        "to this many microseconds (or _BATCH_BYTES) and flush as ONE "
        "buffered write — the BatchingConn size/linger discipline applied "
        "to the journal file.  0 = write-per-append (the pre-batching "
        "behavior); a SIGKILL can lose at most the unflushed window, the "
        "same contract wire linger has",
    ),
    "gcs_journal_batch_bytes": (
        64 * 1024, int,
        "journal group-commit size trigger: pending entry bytes at which "
        "the batch flushes immediately instead of waiting for the linger",
    ),
    "ready_queue_spill_after": (
        100000, int,
        "head ready-queue backlog (tasks) beyond which newly-submitted "
        "dependency-free plain tasks spill their specs to a disk segment "
        "next to the GCS snapshot instead of living in head memory; "
        "reloaded in dispatch-order chunks as the backlog drains.  Bounds "
        "head RSS under a 1M-task backlog (the reference absorbs the same "
        "backlog through its distributed raylet queues); 0 disables "
        "spilling",
    ),
    "wire_stats": (
        0, int,
        "1 = expose per-process wire counters (logical frames, physical "
        "writes, bytes, flush-reason histogram) through the state API / "
        "dashboard, emit them as a cluster event at shutdown, and have "
        "workers report theirs to the head (counting itself is always on)",
    ),
    "fault_spec": (
        "", str,
        "deterministic fault-injection plan (faults.py grammar: "
        "'<point>:<action>[@sel,...];...'); empty = injection disabled "
        "(zero-overhead fast path; ray: RayConfig testing knobs like "
        "testing_asio_delay_us)",
    ),
    "fault_seed": (
        0, int,
        "seed for the fault plan's prob= selectors — the same spec+seed "
        "replays the same injection schedule (print it on failure, rerun "
        "to reproduce)",
    ),
    "metrics_push_ms": (
        1000, int,
        "how often every process (workers, daemons, attached drivers, the "
        "head itself) snapshots its util/metrics registry + wire counters "
        "and ships it to the head as a droppable oneway riding the v2 "
        "batch frames; 0 disables the push (ray: "
        "metrics_report_interval_ms, the OpenCensus export tick)",
    ),
    "telemetry_ring_samples": (
        360, int,
        "head-side bound on each aggregated metric's time series ring "
        "(samples retained at the push period — 360 x 1s = 6 minutes; "
        "ray: the GcsTaskManager ring-storage idiom applied to metrics)",
    ),
    "flight_ring_size": (
        512, int,
        "per-process flight-recorder ring: recent telemetry events "
        "(spans, metric-push deltas, fault injections, cluster events) "
        "retained in memory for a crash dump",
    ),
    "flight_dir": (
        "", str,
        "directory flight-recorder rings dump to (per-pid JSONL files) on "
        "crash, lock-watchdog report, or fault-plane kill; empty disables "
        "dumping (the ring still records)",
    ),
    "refs_push": (
        1, int,
        "1 = every worker/driver ships its live ObjectRef table (oid, "
        "count, creation site) to the head's object ledger each telemetry "
        "tick as a droppable refs_push oneway (requires metrics_push_ms "
        "> 0); 0 disables the ref-table leg only (ray: the per-worker "
        "ReferenceCounter tables `ray memory` joins, reference_count.h:61)",
    ),
    "ref_callsite": (
        0, int,
        "1 = capture the creation site (first non-ray_tpu stack frame) of "
        "every ObjectRef into the live-ref table, enabling `ray_tpu memory "
        "--group-by callsite`; off by default — a frame walk per ref on "
        "the hot path (ray: RAY_record_ref_creation_sites)",
    ),
    "leak_reclaim_grace_s": (
        3.0, float,
        "how long a crashed process's outstanding ref borrows stay as "
        "attributed LEAK SUSPECTS in the object ledger before the head "
        "reclaims them (decref + free); the window in which `ray_tpu "
        "memory --leaks` can attribute leaked bytes to the dead holder's "
        "node/pid",
    ),
    "leak_orphan_reclaim_s": (
        20.0, float,
        "how long a NO-LIVE-HOLDER leak suspect (located ready bytes at "
        "refcount 0 that no live process's ref table claims) must stay "
        "flagged across ledger ticks before the head frees it (0 = never "
        "auto-free).  Covers the head-bounce retention gap: a re-driven "
        "task's result seals at refcount 0 on the restarted head, and a "
        "driver that already dropped its ref can never free it — each "
        "reclaim is a WARNING event, visible, not papered over",
    ),
    "leak_age_s": (
        10.0, float,
        "minimum object age before located bytes with refcount 0 and no "
        "live holder count as a leak suspect (younger objects are in the "
        "legitimate seal-to-first-addref window)",
    ),
    "object_events_max": (
        4096, int,
        "bound on the head's object lifecycle event ring (create/seal/"
        "transfer/spill/restore/free records merged into the chrome "
        "timeline)",
    ),
    "head_io_shards": (
        0, int,
        "number of io-shard processes the head fans its connection fabric "
        "across: each shard owns a slice of the worker/daemon/driver conns "
        "(handed off by conn-hash after the auth handshake), runs its own "
        "epoll loop + protocol-v2 decode/encode, and forwards only decoded "
        "control messages to the head over one batched channel; 0 = the "
        "classic in-process io loop (single-core behavior unchanged) "
        "(ray: the gRPC server thread pools in gcs_server)",
    ),
    "io_shard_restart_s": (
        0.5, float,
        "backoff before the head respawns a dead io shard; its conns fail "
        "over immediately (peers reconnect and hash onto live shards)",
    ),
    "io_shard_pending_send_s": (
        30.0, float,
        "how long an io shard buffers head->conn sends for a conn whose "
        "fd handoff has not arrived yet (the two ride different channels "
        "and may reorder) before dropping them as dead-conn traffic",
    ),
    "zygote_fork_grace_s": (
        20.0, float,
        "how long a zygote-forked worker handle with no pid attribution "
        "yet reads alive before the reaper declares the fork lost and "
        "reschedules its lease",
    ),
    "actor_adopt_grace_s": (
        5.0, float,
        "after a head restart, how long restored detached/named actors "
        "wait for their live worker to reconnect (state preserved) before "
        "being respawned from their creation spec (state reset)",
    ),
    "prof_hz": (
        0.0, float,
        "sampling-profiler autostart rate: every process starts its "
        "sys._current_frames() sampler at this many Hz at entry "
        "(profiler.py; the chaos soak's always-hot mode).  0 = off — the "
        "zero-overhead default; `ray_tpu profile` still starts sampling "
        "cluster-wide on demand via a pubsub broadcast "
        "(ray: the dashboard's py-spy attach plays this role)",
    ),
    "timeline_last_s": (
        0.0, float,
        "default window for the chrome-trace timeline export: only "
        "events/spans newer than this many seconds are emitted (0 = "
        "everything the rings hold); `ray_tpu timeline --last/--since` "
        "override per call",
    ),
    "remesh_wait_s": (
        30.0, float,
        "elastic MESH gangs: after a member host dies, how long the "
        "reshape sweep waits for a replacement host before re-planning a "
        "smaller contiguous box at N-1 (wait-vs-shrink policy; 0 = shrink "
        "immediately)",
    ),
    "autoscale_enabled": (
        0, int,
        "1 = the head attaches the demand-driven autoscaler "
        "(_private/autoscaler.py) at boot: a reconcile loop grows the "
        "node fleet toward unmet demand and drains idle nodes back to "
        "the floor; infeasible tasks PARK instead of erroring while it "
        "is on (the fleet may grow to fit them)",
    ),
    "autoscale_interval_s": (
        0.5, float,
        "autoscaler reconcile period: how often demand is compared "
        "against the fleet (each tick runs OFF the runtime lock)",
    ),
    "autoscale_min_nodes": (
        0, int,
        "autoscaler floor: provider-managed worker nodes are never "
        "drained below this count (the head node is not counted)",
    ),
    "autoscale_max_nodes": (
        4, int,
        "autoscaler ceiling: at most this many provider-managed worker "
        "nodes exist at once, however deep the unmet demand",
    ),
    "autoscale_up_wait_s": (
        1.0, float,
        "launch hysteresis: demand must stay unmet this long before a "
        "node launch — a burst the current fleet absorbs within the "
        "window never scales up",
    ),
    "autoscale_idle_s": (
        10.0, float,
        "drain hysteresis: a provider-managed node must sit fully idle "
        "(no running tasks, no actors, no held leases) this long before "
        "the autoscaler starts draining it",
    ),
    "autoscale_launch_timeout_s": (
        30.0, float,
        "a REQUESTED/STARTING node that has not registered within this "
        "window is declared failed: its process is terminated and the "
        "slot retried",
    ),
    "autoscale_drain_timeout_s": (
        30.0, float,
        "drain patience: how long a DRAINING node may wait for its "
        "running tasks to finish before the daemon departs anyway (the "
        "in-flight tasks then re-drive on their retry budget, exactly "
        "like a node death)",
    ),
}

# Back-compat env names from before the knob table existed, plus the
# short spellings the docs use for the fast-path knobs.
_ENV_ALIASES: Dict[str, tuple] = {
    "lineage_max_entries": ("RAY_TPU_LINEAGE_MAX",),
    "lineage_max_bytes": ("RAY_TPU_LINEAGE_MAX_BYTES",),
    "task_lease_idle_s": ("RAY_TPU_LEASE_IDLE_S",),
    "gcs_journal_flush_us": ("RAY_TPU_JOURNAL_FLUSH_US",),
    "gcs_journal_batch_bytes": ("RAY_TPU_JOURNAL_BATCH_BYTES",),
}

# Process-wiring environment variables: NOT knobs.  These carry bootstrap
# plumbing between processes (spawn-time identity, fds, endpoints) or are
# read before the config table can be imported (early-boot toggles), so
# they are accessed directly via os.environ rather than config.get().
# Declared here so the knob-registry lint can tell a deliberate wiring
# access from a typo'd knob name (which silently no-ops).  Adding an env
# var that is neither a knob nor declared here fails the lint.
WIRING_ENV: Dict[str, str] = {
    # spawn-time identity / topology (parent -> child)
    "RAY_TPU_DRIVER_HOST": "head endpoint host handed to spawned processes",
    "RAY_TPU_DRIVER_PORT": "head endpoint port handed to spawned processes",
    "RAY_TPU_AUTHKEY": "hex cluster authkey handed to spawned processes",
    "RAY_TPU_SESSION": "session id handed to spawned processes",
    "RAY_TPU_WORKER_ID": "this worker's id (set by the spawning daemon)",
    "RAY_TPU_NODE_ID": "this node's id (set by the spawning daemon)",
    "RAY_TPU_NODE_CONFIG": "JSON node spec for a starting node daemon",
    "RAY_TPU_HEAD_CONFIG": "JSON head spec for `ray_tpu head` boot",
    "RAY_TPU_IO_SHARD_CONFIG": "JSON shard spec for a forked io shard",
    "RAY_TPU_PEER_HOST": "host the worker's direct-call listener binds",
    "RAY_TPU_HOST_IP": "this host's routable IP (parallel bootstrap)",
    "RAY_TPU_STORE_DIR": "shm store directory handed to spawned processes",
    "RAY_TPU_RUNTIME_ENV": "JSON runtime_env applied at worker boot",
    "RAY_TPU_ENV_VARS": "JSON extra env vars applied at worker boot",
    # inherited descriptors (SCM_RIGHTS / fork plumbing)
    "RAY_TPU_ZYGOTE_FD": "inherited zygote control-pipe fd number",
    "RAY_TPU_ARENA_FD": "inherited shm arena fd number",
    # early-boot / dev toggles read before config import is safe
    "RAY_TPU_TRACE": "1 = per-op wall-clock tracing to stderr",
    "RAY_TPU_BOOT_TRACE": "1 = worker boot-phase timing to stderr",
    "RAY_TPU_DEBUG_LOCKS": "1 = slow-lock diagnostics in the runtime",
    "RAY_TPU_FAULTHANDLER": "1 = arm faulthandler in spawned workers",
    "RAY_TPU_PDEATHSIG": "0 = skip parent-death signal on Linux children",
    "RAY_TPU_CHIPS": "override detected accelerator chip count",
    "RAY_TPU_LOCK_WATCHDOG": "1 = swap hot locks for instrumented wrappers",
    "RAY_TPU_LOCK_HOLD_S": "lock-watchdog long-hold threshold (seconds)",
    "RAY_TPU_LOCK_WATCHDOG_DIR": "per-pid lock-watchdog report directory",
    # cache locations
    "RAY_TPU_NATIVE_CACHE": "build cache dir for the native arena module",
    "RAY_TPU_PKG_CACHE": "download cache dir for runtime_env packages",
    # bench plumbing
    "RAY_TPU_PERF_PERSIST": "keep ray_perf scratch dirs for inspection",
}

_lock = threading.Lock()
_values: Dict[str, Any] = {}
_frozen_overrides: Dict[str, Any] = {}


def set_system_config(overrides: Dict[str, Any]) -> None:
    """Programmatic overrides (ray: ray.init(_system_config=...)); applied
    before first access wins over env vars."""
    unknown = set(overrides) - set(_DEFS)
    if unknown:
        raise ValueError(f"unknown config keys {sorted(unknown)}; valid: {sorted(_DEFS)}")
    coerced = {}
    for k, v in overrides.items():
        typ = _DEFS[k][1]
        try:
            coerced[k] = typ(v)
        except (TypeError, ValueError) as e:
            # fail HERE at the init() call site, not later inside Runtime
            raise ValueError(f"config {k!r} expects {typ.__name__}, got {v!r}") from e
    with _lock:
        _frozen_overrides.update(coerced)
        for k, v in coerced.items():
            _values.pop(k, None)  # recompute on next access
            # Children (workers/daemons) inherit os.environ, not this
            # in-process table: export the env form so worker-side knobs
            # (handshake timeout, inline threshold, native store) actually
            # take effect there.
            os.environ[f"RAY_TPU_{k.upper()}"] = str(v)


def get(name: str):
    """Resolve a knob: _system_config > RAY_TPU_<NAME> env > default."""
    try:
        default, typ, _doc = _DEFS[name]
    except KeyError:
        raise KeyError(f"unknown config {name!r}; valid: {sorted(_DEFS)}")
    # Lock-free fast path (GIL-atomic dict read): get() sits on hot paths
    # like per-result inline_threshold checks.
    try:
        return _values[name]
    except KeyError:
        pass
    with _lock:
        if name in _values:
            return _values[name]
        if name in _frozen_overrides:
            val = _frozen_overrides[name]
        else:
            env = os.environ.get(f"RAY_TPU_{name.upper()}")
            if env is None:
                for alias in _ENV_ALIASES.get(name, ()):
                    env = os.environ.get(alias)
                    if env is not None:
                        break
            if env is not None:
                try:
                    val = typ(env)
                except ValueError:
                    val = default
            else:
                val = default
        _values[name] = val
        return val


def describe() -> Dict[str, Dict[str, Any]]:
    """Every knob with default, current value, and doc (ray: the config
    dump the dashboard shows)."""
    return {
        name: {"default": d, "value": get(name), "doc": doc}
        for name, (d, _t, doc) in _DEFS.items()
    }


def _reset_for_tests() -> None:
    with _lock:
        _values.clear()
        _frozen_overrides.clear()
