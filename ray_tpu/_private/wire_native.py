"""Native hot-frame codec: struct-framed, data-only bodies — no pickle.

ray: src/ray/protobuf/common.proto — the reference's hot control frames
(task pushes, task done, ref-count ops, resource/metric reports) are typed
protobuf messages: decoding one constructs plain structs, never arbitrary
objects, and the schema is the wire contract.  Ours spoke pickle for every
frame, which costs more than it looks: pickling a TaskSpec dataclass
serializes the class reference and every FIELD NAME per task (~750 bytes,
~11µs encode + ~14µs decode), and unpickling executes the full object-
construction machinery on the single-writer head for every hot frame.

This module is the pickle-free path for the half-dozen hottest frame
kinds.  A native body is

    u8 kind_id (1..0x7F) | u8 marshal_version | marshal(payload)

where `payload` is a plain data tuple (the TaskSpec rides as a positional
FIELD TUPLE, not an object) and `marshal` is CPython's C serializer for
code-free data: ~0.8µs/spec each way, 14–17x faster than the dataclass
pickle, and — like protobuf — decoding can only ever build
None/bool/int/float/str/bytes/list/tuple/dict, never invoke a
constructor or reducer.  The first body byte disambiguates from pickle
(whose protocol-2+ streams always start with 0x80), so native and
pickled bodies coexist per frame inside the existing v3 framing; see
wire.py for the negotiation/fallback rule.

Fallback contract: `encode(obj)` returns None whenever the frame doesn't
fit the packed schema — unknown kind, unexpected arity, a payload value
marshal can't take (e.g. a scheduling-strategy instance, an exception in
a reply) — and the caller pickles instead.  Decode is strict: a
malformed native body raises ProtocolError, the same boundary rejection
a bad pickled frame gets.
"""

from __future__ import annotations

import marshal
from typing import Any, Optional

MARSHAL_VERSION = marshal.version

# kind_id registry.  Stable small ints — these are on the wire.  0x80 is
# forbidden (pickle's protocol marker is the discriminator byte).
KIND_IDS = {
    "refop": 1,
    "done": 2,
    "task": 3,
    "create_actor": 4,
    "pcall": 5,
    "pdone": 6,
    "task_events": 7,
    "metrics_push": 8,
    "refs_push": 9,
    "prof_push": 10,
    "spans": 11,
    "shard_fwd": 12,
    "shard_send": 13,
    "reply": 14,
    "heartbeat": 15,
    "direct_seal": 16,
    "direct_lineage": 17,
    "lease_return": 18,
}
_ID_KINDS = {v: k for k, v in KIND_IDS.items()}

# TaskSpec rides as a positional field tuple: the field list is resolved
# once (import order: task_spec has no wire dependency) and its LENGTH is
# part of the decode check — a spec tuple of any other arity is a skewed
# peer and must reject loudly, not build a shifted spec.
_SPEC_FIELDS: Optional[tuple] = None
_SPEC_GETTER = None


def _spec_fields() -> tuple:
    global _SPEC_FIELDS, _SPEC_GETTER
    if _SPEC_FIELDS is None:
        import dataclasses
        import operator

        from ray_tpu._private.task_spec import TaskSpec

        _SPEC_FIELDS = tuple(f.name for f in dataclasses.fields(TaskSpec))
        _SPEC_GETTER = operator.itemgetter(*_SPEC_FIELDS)
    return _SPEC_FIELDS


def spec_to_tuple(spec) -> Optional[tuple]:
    """Positional field tuple, or None when a field can't ride marshal
    (strategy objects fall back to pickle; plain str/None strategies — the
    hot shapes — pack).  itemgetter walks the instance dict at C speed —
    this runs once per task push."""
    if _SPEC_GETTER is None:
        _spec_fields()
    try:
        return _SPEC_GETTER(spec.__dict__)
    except KeyError:
        return None  # subclass / skewed instance: pickle knows best


def tuple_to_spec(t: tuple):
    from ray_tpu._private.task_spec import TaskSpec

    fields = _spec_fields()
    if len(t) != len(fields):
        raise ProtocolError(
            f"native TaskSpec has {len(t)} fields, this build expects "
            f"{len(fields)} — mixed-version cluster"
        )
    spec = TaskSpec.__new__(TaskSpec)
    spec.__dict__.update(zip(fields, t))
    return spec


class ProtocolError(ConnectionError):
    """Raised on malformed native bodies (wire.py re-exports its own; this
    subclass keeps the module import-light and is caught as
    ConnectionError everywhere conns die)."""


_SAFE_SCALARS = (type(None), bool, int, float, str, bytes)


def _data_safe(v, _depth: int = 0) -> bool:
    """EXACT-type recursive check for user-influenced payload positions.
    marshal silently serializes container SUBCLASSES as their base type
    (a SampleBatch(dict) would come back a plain dict); positions our own
    code builds are exact by construction, but user-reachable ones
    (reply values, runtime_env) must verify or fall back to pickle."""
    t = type(v)
    if t in _SAFE_SCALARS:
        return True
    if _depth > 16:
        return False
    if t is dict:
        return all(
            _data_safe(k, _depth + 1) and _data_safe(x, _depth + 1)
            for k, x in v.items()
        )
    if t is list or t is tuple:
        return all(_data_safe(x, _depth + 1) for x in v)
    return False


def _spec_safe(spec) -> bool:
    """The user-influenced spec fields (everything else is built by the
    submit machinery with exact types; args_blob is opaque bytes)."""
    return (
        type(spec.resources) is dict
        and (spec.runtime_env is None or _data_safe(spec.runtime_env))
    )


def _payload(obj: tuple) -> Any:
    """Frame tuple -> marshal-ready payload, or the _UNSUPPORTED sentinel.
    Per-kind shaping keeps decode strict and specs positional."""
    kind = obj[0]
    if kind in ("task", "create_actor"):
        # ("task", spec, blob)
        if len(obj) != 3:
            return _UNSUPPORTED
        st = spec_to_tuple(obj[1])
        if st is None or not _spec_safe(obj[1]):
            return _UNSUPPORTED
        return (st, obj[2])
    if kind == "pcall":
        # ("pcall", spec) — the direct-push twin of "task"
        if len(obj) != 2:
            return _UNSUPPORTED
        st = spec_to_tuple(obj[1])
        if st is None or not _spec_safe(obj[1]):
            return _UNSUPPORTED
        return (st,)
    if kind == "reply":
        # ("reply", req_id, ok, value) — value is op-defined and may be
        # or contain anything (exceptions, refs, user returns).
        if len(obj) != 4 or not _data_safe(obj[3]):
            return _UNSUPPORTED
        return obj[1:]
    return obj[1:]


_UNSUPPORTED = object()


def encode(obj: Any) -> Optional[bytes]:
    """Native body for a control tuple, or None -> caller pickles."""
    if not (isinstance(obj, tuple) and obj and isinstance(obj[0], str)):
        return None
    kid = KIND_IDS.get(obj[0])
    if kid is None:
        return None
    payload = _payload(obj)
    if payload is _UNSUPPORTED:
        return None
    try:
        body = marshal.dumps(payload, 2)
    except ValueError:
        return None  # a field marshal can't take: pickle fallback
    return bytes((kid, MARSHAL_VERSION)) + body


def kind_of(body) -> Optional[str]:
    """Peek a body's control kind WITHOUT decoding: native bodies carry it
    in byte 0; pickled bodies (0x80...) return None — the caller must
    decode to learn the kind.  Used by the io shards to forward native
    bodies raw and by fault/stat scoping."""
    if not body:
        return None
    b0 = body[0]
    if b0 == 0x80:
        return None
    return _ID_KINDS.get(b0)


def is_native(body) -> bool:
    return bool(body) and body[0] != 0x80


def decode(body) -> Any:
    """Strict decode of a native body back into the control tuple."""
    if len(body) < 3:
        raise ProtocolError("truncated native frame body")
    kid, mver = body[0], body[1]
    kind = _ID_KINDS.get(kid)
    if kind is None:
        raise ProtocolError(f"unknown native frame kind id {kid}")
    if mver != MARSHAL_VERSION:
        raise ProtocolError(
            f"native codec version skew: peer marshal v{mver}, this "
            f"interpreter v{MARSHAL_VERSION} — run matching Pythons or "
            "set RAY_TPU_WIRE_NATIVE=0"
        )
    try:
        payload = marshal.loads(bytes(body[2:]))
    except (ValueError, EOFError, TypeError) as e:
        raise ProtocolError(f"malformed native {kind!r} body: {e}") from None
    if not isinstance(payload, tuple):
        raise ProtocolError(f"native {kind!r} payload is not a tuple")
    if kind in ("task", "create_actor"):
        if len(payload) != 2 or not isinstance(payload[0], tuple):
            raise ProtocolError(f"native {kind!r} payload shape")
        return (kind, tuple_to_spec(payload[0]), payload[1])
    if kind == "pcall":
        if len(payload) != 1 or not isinstance(payload[0], tuple):
            raise ProtocolError("native 'pcall' payload shape")
        return (kind, tuple_to_spec(payload[0]))
    return (kind,) + payload
