"""Native hot-frame codec: struct-framed, data-only bodies — no pickle.

ray: src/ray/protobuf/common.proto — the reference's hot control frames
(task pushes, task done, ref-count ops, resource/metric reports) are typed
protobuf messages: decoding one constructs plain structs, never arbitrary
objects, and the schema is the wire contract.  Ours spoke pickle for every
frame, which costs more than it looks: pickling a TaskSpec dataclass
serializes the class reference and every FIELD NAME per task (~750 bytes,
~11µs encode + ~14µs decode), and unpickling executes the full object-
construction machinery on the single-writer head for every hot frame.

This module is the pickle-free path for the half-dozen hottest frame
kinds.  A native body is

    u8 kind_id (1..0x7F) | u8 marshal_version | marshal(payload)

where `payload` is a plain data tuple (the TaskSpec rides as a positional
FIELD TUPLE, not an object) and `marshal` is CPython's C serializer for
code-free data: ~0.8µs/spec each way, 14–17x faster than the dataclass
pickle, and — like protobuf — decoding can only ever build
None/bool/int/float/str/bytes/list/tuple/dict, never invoke a
constructor or reducer.  The first body byte disambiguates from pickle
(whose protocol-2+ streams always start with 0x80), so native and
pickled bodies coexist per frame inside the existing v3 framing; see
wire.py for the negotiation/fallback rule.

Fallback contract: `encode(obj)` returns None whenever the frame doesn't
fit the packed schema — unknown kind, unexpected arity, a payload value
marshal can't take (e.g. a scheduling-strategy instance, an exception in
a reply) — and the caller pickles instead.  Decode is strict: a
malformed native body raises ProtocolError, the same boundary rejection
a bad pickled frame gets.  Strict includes BOUNDED: marshal.loads
pre-allocates containers/strings at their declared size, so decode first
walks the stream and rejects any body whose declared sizes outrun its
bytes (see _scan_payload) — without it, an 11-byte body can make the
head zero out gigabytes.  RAY_TPU_WIRE_GUARD=0 disables the walk on
trusted fabrics.
"""

from __future__ import annotations

import marshal
import struct as _struct
from typing import Any, Optional

MARSHAL_VERSION = marshal.version

# kind_id registry.  Stable small ints — these are on the wire.  0x80 is
# forbidden (pickle's protocol marker is the discriminator byte).
KIND_IDS = {
    "refop": 1,
    "done": 2,
    "task": 3,
    "create_actor": 4,
    "pcall": 5,
    "pdone": 6,
    "task_events": 7,
    "metrics_push": 8,
    "refs_push": 9,
    "prof_push": 10,
    "spans": 11,
    "shard_fwd": 12,
    "shard_send": 13,
    "reply": 14,
    "heartbeat": 15,
    "direct_seal": 16,
    "direct_lineage": 17,
    "lease_return": 18,
}
_ID_KINDS = {v: k for k, v in KIND_IDS.items()}

# Kinds whose payload _payload()/decode() shape at an EXACT extra-field
# arity (everything else passes obj[1:] through unchanged, so the wire
# schema alone bounds it).  The wire-schema lint cross-checks this table
# against wire.SCHEMAS — drift between the two is a frame that encodes
# here and fails validation there.
NATIVE_ARITIES = {
    "task": 2,          # (spec, blob)
    "create_actor": 2,  # (spec, blob)
    "pcall": 1,         # (spec,)
    "reply": 3,         # (req_id, ok, value)
}

# TaskSpec rides as a positional field tuple: the field list is resolved
# once (import order: task_spec has no wire dependency) and its LENGTH is
# part of the decode check — a spec tuple of any other arity is a skewed
# peer and must reject loudly, not build a shifted spec.
_SPEC_FIELDS: Optional[tuple] = None
_SPEC_GETTER = None


def _spec_fields() -> tuple:
    global _SPEC_FIELDS, _SPEC_GETTER
    if _SPEC_FIELDS is None:
        import dataclasses
        import operator

        from ray_tpu._private.task_spec import TaskSpec

        _SPEC_FIELDS = tuple(f.name for f in dataclasses.fields(TaskSpec))
        _SPEC_GETTER = operator.itemgetter(*_SPEC_FIELDS)
    return _SPEC_FIELDS


def spec_to_tuple(spec) -> Optional[tuple]:
    """Positional field tuple, or None when a field can't ride marshal
    (strategy objects fall back to pickle; plain str/None strategies — the
    hot shapes — pack).  itemgetter walks the instance dict at C speed —
    this runs once per task push."""
    if _SPEC_GETTER is None:
        _spec_fields()
    try:
        return _SPEC_GETTER(spec.__dict__)
    except (KeyError, AttributeError, TypeError):
        # Skewed/subclassed instance — or not a spec object at all (a
        # malformed frame must DECLINE to pickle, never crash encode).
        return None


def tuple_to_spec(t: tuple):
    from ray_tpu._private.task_spec import TaskSpec

    fields = _spec_fields()
    if len(t) != len(fields):
        raise ProtocolError(
            f"native TaskSpec has {len(t)} fields, this build expects "
            f"{len(fields)} — mixed-version cluster"
        )
    spec = TaskSpec.__new__(TaskSpec)
    spec.__dict__.update(zip(fields, t))
    return spec


class ProtocolError(ConnectionError):
    """Raised on malformed native bodies (wire.py re-exports its own; this
    subclass keeps the module import-light and is caught as
    ConnectionError everywhere conns die)."""


_SAFE_SCALARS = (type(None), bool, int, float, str, bytes)


def _data_safe(v, _depth: int = 0) -> bool:
    """EXACT-type recursive check for user-influenced payload positions.
    marshal silently serializes container SUBCLASSES as their base type
    (a SampleBatch(dict) would come back a plain dict); positions our own
    code builds are exact by construction, but user-reachable ones
    (reply values, runtime_env) must verify or fall back to pickle."""
    t = type(v)
    if t in _SAFE_SCALARS:
        return True
    if _depth > 16:
        return False
    if t is dict:
        return all(
            _data_safe(k, _depth + 1) and _data_safe(x, _depth + 1)
            for k, x in v.items()
        )
    if t is list or t is tuple:
        return all(_data_safe(x, _depth + 1) for x in v)
    return False


def _spec_safe(spec) -> bool:
    """The user-influenced spec fields (everything else is built by the
    submit machinery with exact types; args_blob is opaque bytes)."""
    return (
        type(spec.resources) is dict
        and (spec.runtime_env is None or _data_safe(spec.runtime_env))
    )


def _payload(obj: tuple) -> Any:
    """Frame tuple -> marshal-ready payload, or the _UNSUPPORTED sentinel.
    Per-kind shaping keeps decode strict and specs positional."""
    kind = obj[0]
    if kind in ("task", "create_actor"):
        # ("task", spec, blob)
        if len(obj) != 3:
            return _UNSUPPORTED
        st = spec_to_tuple(obj[1])
        if st is None or not _spec_safe(obj[1]):
            return _UNSUPPORTED
        return (st, obj[2])
    if kind == "pcall":
        # ("pcall", spec) — the direct-push twin of "task"
        if len(obj) != 2:
            return _UNSUPPORTED
        st = spec_to_tuple(obj[1])
        if st is None or not _spec_safe(obj[1]):
            return _UNSUPPORTED
        return (st,)
    if kind == "reply":
        # ("reply", req_id, ok, value) — value is op-defined and may be
        # or contain anything (exceptions, refs, user returns).
        if len(obj) != 4 or not _data_safe(obj[3]):
            return _UNSUPPORTED
        return obj[1:]
    return obj[1:]


_UNSUPPORTED = object()


def encode(obj: Any) -> Optional[bytes]:
    """Native body for a control tuple, or None -> caller pickles."""
    if not (isinstance(obj, tuple) and obj and isinstance(obj[0], str)):
        return None
    kid = KIND_IDS.get(obj[0])
    if kid is None:
        return None
    payload = _payload(obj)
    if payload is _UNSUPPORTED:
        return None
    try:
        body = marshal.dumps(payload, 2)
    except ValueError:
        return None  # a field marshal can't take: pickle fallback
    return bytes((kid, MARSHAL_VERSION)) + body


def kind_of(body) -> Optional[str]:
    """Peek a body's control kind WITHOUT decoding: native bodies carry it
    in byte 0; pickled bodies (0x80...) return None — the caller must
    decode to learn the kind.  Used by the io shards to forward native
    bodies raw and by fault/stat scoping."""
    if not body:
        return None
    b0 = body[0]
    if b0 == 0x80:
        return None
    return _ID_KINDS.get(b0)


def is_native(body) -> bool:
    return bool(body) and body[0] != 0x80


# Allocation guard.  marshal.loads allocates each container/string at its
# DECLARED size before reading a single element: an 11-byte body whose
# payload is `28 00 10 00 20` (tuple opcode, count 0x20100000) makes
# r_object zero out a ~4 GB tuple on the single-writer head — a one-frame
# allocation bomb from any corrupted or hostile peer.  _scan_payload
# walks the stream first and verifies every declared length/count fits
# the bytes actually present (each element costs >= 1 byte, so a count
# can never exceed the remaining payload), keeping loads' allocation
# O(len(body)).  Codes outside the data subset our encoder (marshal
# version 2) emits — refs, code objects, legacy spellings — reject: we
# never produce them, so receiving one is skew or corruption, not data.
#
# Action table, indexed by type-code byte: >= 0 is a fixed byte count to
# skip; negatives select a header shape.  FLAG_REF'd codes (0x80 bit) and
# unknown codes stay _A_BAD.
_A_BAD, _A_STR32, _A_STR8, _A_SEQ, _A_DICT, _A_NULL, _A_LONG = (
    -1, -2, -3, -4, -5, -6, -7,
)
_M_ACTIONS = [_A_BAD] * 256
for _c in b"NTFS.":      # None / True / False / StopIteration / Ellipsis
    _M_ACTIONS[_c] = 0
_M_ACTIONS[ord("i")] = 4    # int32
_M_ACTIONS[ord("I")] = 8    # int64 (legacy)
_M_ACTIONS[ord("g")] = 8    # binary float
_M_ACTIONS[ord("y")] = 16   # binary complex
for _c in b"sutaA":      # bytes / unicode / interned / ascii: u32 len
    _M_ACTIONS[_c] = _A_STR32
for _c in b"zZ":         # short ascii: u8 len
    _M_ACTIONS[_c] = _A_STR8
for _c in b"([<>":       # tuple / list / set / frozenset: i32 count
    _M_ACTIONS[_c] = _A_SEQ
_M_ACTIONS[ord("{")] = _A_DICT   # dict: items until NULL key
_M_ACTIONS[ord("0")] = _A_NULL   # TYPE_NULL: dict terminator only
_M_ACTIONS[ord("l")] = _A_LONG   # long: i32 digit count, 2 bytes each
del _c

_I32 = _struct.Struct("<i")


def _scan_payload(data) -> None:
    """Bounds-check a marshal stream without materializing it.

    Linear walk over the type-code stream: every declared string length
    and container count must fit the bytes that remain (an element costs
    >= 1 byte), and the CUMULATIVE declared allocation must stay O(n) —
    nested containers each bounded by `remaining` could otherwise still
    sum to O(n^2).  Grammar (matching counts, balanced dicts) is left to
    marshal.loads, which raises cleanly once allocation is bounded; this
    pass only guarantees loads can't allocate disproportionately and
    that only data-subset codes appear."""
    if type(data) is not bytes:
        data = bytes(data)
    n = len(data)
    pos = 0
    alloc = 0
    limit = 32 * n + 4096  # declared slots+bytes a legit body could need
    actions = _M_ACTIONS
    unpack = _I32.unpack_from
    while pos < n:
        act = actions[data[pos]]
        pos += 1
        if act >= 0:  # fixed-width scalar; overrun lands on the final check
            pos += act
            continue
        if act == _A_STR32:
            if pos + 4 > n:
                raise ProtocolError("truncated marshal string header")
            ln, = unpack(data, pos)
            pos += 4
            if ln < 0 or ln > n - pos:
                raise ProtocolError(
                    f"marshal string declares {ln} bytes, {n - pos} remain"
                )
            pos += ln
            continue
        if act == _A_SEQ:
            if pos + 4 > n:
                raise ProtocolError("truncated marshal container header")
            cnt, = unpack(data, pos)
            pos += 4
            if cnt < 0 or cnt > n - pos:
                raise ProtocolError(
                    f"marshal container declares {cnt} items, only "
                    f"{n - pos} bytes remain — allocation bomb"
                )
            alloc += cnt * 8
            if alloc > limit:
                raise ProtocolError(
                    "marshal body declares allocations far beyond its size"
                )
            continue
        if act == _A_STR8:
            if pos >= n:
                raise ProtocolError("truncated marshal string header")
            pos += 1 + data[pos]
            continue
        if act == _A_LONG:
            if pos + 4 > n:
                raise ProtocolError("truncated marshal long")
            cnt, = unpack(data, pos)
            pos += 4 + 2 * (cnt if cnt >= 0 else -cnt)
            continue
        if act == _A_DICT or act == _A_NULL:
            continue
        code = data[pos - 1]
        if code & 0x80:
            raise ProtocolError(
                "marshal ref flag outside the wire data subset"
            )
        raise ProtocolError(
            f"marshal type code {code:#x} outside the wire data subset"
        )
    if pos != n:
        raise ProtocolError("truncated marshal body")


# Resolved once per process (config.get caches too; this skips even the
# call).  RAY_TPU_WIRE_GUARD=0 trusts the fabric and decodes unguarded.
_GUARD: Optional[bool] = None


def _guard_enabled() -> bool:
    global _GUARD
    if _GUARD is None:
        from ray_tpu._private import config

        _GUARD = bool(config.get("wire_guard"))
    return _GUARD


def decode(body) -> Any:
    """Strict decode of a native body back into the control tuple."""
    if len(body) < 3:
        raise ProtocolError("truncated native frame body")
    kid, mver = body[0], body[1]
    kind = _ID_KINDS.get(kid)
    if kind is None:
        raise ProtocolError(f"unknown native frame kind id {kid}")
    if mver != MARSHAL_VERSION:
        raise ProtocolError(
            f"native codec version skew: peer marshal v{mver}, this "
            f"interpreter v{MARSHAL_VERSION} — run matching Pythons or "
            "set RAY_TPU_WIRE_NATIVE=0"
        )
    guard = _GUARD
    if guard is None:
        guard = _guard_enabled()
    if guard:
        _scan_payload(body[2:])
    try:
        payload = marshal.loads(bytes(body[2:]))
    except (ValueError, EOFError, TypeError, MemoryError) as e:
        # MemoryError: with the guard off, a bomb body that fails its
        # giant allocation still dies as a boundary rejection.
        raise ProtocolError(f"malformed native {kind!r} body: {e}") from None
    if not isinstance(payload, tuple):
        raise ProtocolError(f"native {kind!r} payload is not a tuple")
    if kind in ("task", "create_actor"):
        if len(payload) != 2 or not isinstance(payload[0], tuple):
            raise ProtocolError(f"native {kind!r} payload shape")
        return (kind, tuple_to_spec(payload[0]), payload[1])
    if kind == "pcall":
        if len(payload) != 1 or not isinstance(payload[0], tuple):
            raise ProtocolError("native 'pcall' payload shape")
        return (kind, tuple_to_spec(payload[0]))
    return (kind,) + payload
