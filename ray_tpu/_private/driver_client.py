"""Driver attach: connect this process to a standalone head as a client.

The attached driver reuses the worker-side machinery (WorkerRuntime's
request/reply mux, the object resolution paths, ref hooks) — a driver is a
worker that never executes tasks, exactly how the reference's Ray Client
server funnels a remote driver through the core-worker surface
(ray: python/ray/util/client/ARCHITECTURE.md, util/client/server/).

Two store modes, negotiated at attach:
  * co-located (same host as the head): the driver maps the HEAD store
    directory for zero-copy reads, like any head-node worker;
  * remote: the driver keeps a private store dir and every large object
    rides the control conn (puts) or the transfer plane (gets via pull
    endpoints) — no filesystem assumptions, i.e. the ray:// case.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ray_tpu._private import ids


_attached = None  # the attached WorkerRuntime, if any


def is_attached() -> bool:
    return _attached is not None


def attach(
    address,
    authkey: Optional[str] = None,
    namespace: str = "default",
    shared_store: Optional[bool] = None,
    log_to_driver: bool = True,
):
    """Connect to a head.  `address` is a path to head.json (or its session
    dir), or a "host:port" string with `authkey` passed explicitly."""
    global _attached
    from multiprocessing.connection import Client

    from ray_tpu._private import worker_proc
    from ray_tpu._private.head import read_head_info

    if _attached is not None:
        return _attached
    addr = str(address)
    for scheme in ("ray://", "ray_tpu://"):
        if addr.startswith(scheme):
            # The ray:// client scheme (ray: util/client/ARCHITECTURE.md):
            # a remote driver by definition — never assume the head's
            # filesystem is reachable, whatever the host looks like.
            addr = addr[len(scheme):]
            if shared_store is None:
                shared_store = False
            break
    if os.path.exists(addr):
        info = read_head_info(addr)
        host, port, key = info["host"], int(info["port"]), bytes.fromhex(info["authkey"])
    else:
        if authkey is None:
            raise ValueError(
                f"attaching to {address!r} by host:port requires the head's "
                "authkey: pass ray_tpu.init(address=..., _authkey=...) — "
                "`ray_tpu start --head` prints the full line"
            )
        host, port = addr.rsplit(":", 1)
        key = bytes.fromhex(authkey)
        port = int(port)

    from ray_tpu._private import wire
    from ray_tpu._private.netutil import set_nodelay

    conn = wire.connect((host, port), key)
    set_nodelay(conn)
    did = ids._fresh("drv")
    import time as _time

    conn.send(("driver", did, os.getpid(), _time.time()))
    ack = conn.recv()
    if not (isinstance(ack, tuple) and ack[0] == "driver_ack"):
        conn.close()
        raise ConnectionError(f"unexpected head handshake reply: {ack!r}")
    meta = ack[1]
    session = meta["session"]
    head_store_dir = meta.get("store_dir")
    if shared_store is None:
        shared_store = (
            host in ("127.0.0.1", "localhost")
            and head_store_dir is not None
            and os.path.isdir(head_store_dir)
        )
    conn.send(("driver_store", did, bool(shared_store)))
    # Handshake done: the long-lived conn gets the coalescing sender
    # (refop/put_ow oneway bursts become one write per request flush).
    conn = wire.batching(conn)

    conn_lock = threading.Lock()
    store_dir = (
        head_store_dir
        if shared_store
        else os.path.join("/tmp", f"raytpu-drv-{session}-{did}")
    )
    rt = worker_proc.WorkerRuntime(
        conn, conn_lock, session, did, authkey=key, store_dir=store_dir
    )
    rt.owns_store_dir = not shared_store
    rt.force_inline_puts = not shared_store
    rt.reconnect_window_override = float(meta.get("reconnect_window_s") or 0)
    rt._attach_info = (host, port, key, did, bool(shared_store))
    worker_proc._runtime = rt

    from ray_tpu._private import refs as refs_mod
    from ray_tpu._private import runtime as runtime_mod

    refs_mod.set_ref_hooks(
        lambda oid: rt.oneway(("refop", "add", oid)),
        lambda oid: rt.oneway(("refop", "del", oid)),
    )
    runtime_mod._worker_mode = True

    t = threading.Thread(
        target=_recv_loop, args=(rt,), daemon=True, name="raytpu-driver-recv"
    )
    t.start()
    rt._recv_thread = t
    if log_to_driver:
        # Worker output streams to this driver push-style over the
        # control conn (cross-process pubsub — ray: the driver's print
        # subscriber on the GCS log channel, _private/worker.py).
        rt.subscribe("logs", "*", _print_log_lines)
    _attached = rt
    # Telemetry: the attached driver is a cluster process like any other —
    # flight recorder armed, registry + span buffer pushed to the head on
    # the period.  Started AFTER _attached lands: the loop's liveness
    # check reads it, and a thread racing the assignment would exit
    # before its first push.
    from ray_tpu._private import telemetry

    telemetry.install(f"driver:{did}")
    threading.Thread(
        target=_metrics_push_loop, args=(rt,), daemon=True,
        name="raytpu-driver-telemetry",
    ).start()
    return rt


def _metrics_push_loop(rt) -> None:
    """Periodic telemetry flush for an attached driver (workers push from
    their events ticker; the driver has no executor loop, so it gets its
    own): the metric snapshot AND this process's trace-span buffer — the
    driver's submit:: spans are a leg of the merged cluster timeline.
    Droppable oneways: a head bounce loses ticks, never wedges."""
    import time as _time

    from ray_tpu._private import config as _config
    from ray_tpu._private import telemetry, wire
    from ray_tpu.util import tracing

    period = max(_config.get("metrics_push_ms"), 0) / 1000.0
    if period <= 0:
        return
    push_refs = bool(_config.get("refs_push"))
    while _attached is rt and not getattr(rt, "_detaching", False):
        _time.sleep(period)
        spans = tracing.drain_spans()
        if spans:
            rt.oneway(("spans", spans), droppable=True)
        rt.oneway(("metrics_push", telemetry.snapshot_process()), droppable=True)
        if push_refs:
            # The attached driver's live-ref table is a ledger leg like
            # any worker's — its held refs attribute to this process.
            rt.oneway(("refs_push", rt.ref_table_snapshot()), droppable=True)
        wire.flush_dirty()


def _print_log_lines(wid, stream, lines) -> None:
    import sys as _sys

    from ray_tpu._private.log_monitor import format_log_lines

    try:
        _sys.stdout.write(format_log_lines(wid, stream, lines))
        _sys.stdout.flush()
    except (OSError, ValueError):
        pass  # driver stdout closed


def _try_reconnect(rt) -> bool:
    """Head conn lost: re-attach to the head's FIXED address within its
    reconnect window (a restarted head re-registers this driver and its
    requests re-send — ray: client reconnect after GCS failover)."""
    import time as _time

    from ray_tpu._private import wire
    from ray_tpu._private.netutil import set_nodelay

    window = rt.reconnect_window_override or 0
    if window <= 0 or getattr(rt, "_detaching", False):
        return False
    host, port, key, did, shared = rt._attach_info
    deadline = _time.monotonic() + window
    while _time.monotonic() < deadline:
        if getattr(rt, "_detaching", False):
            return False
        try:
            c = wire.connect((host, port), key)
            set_nodelay(c)
            c.send(("driver", did, os.getpid(), _time.time()))
            ack = c.recv()
            if not (isinstance(ack, tuple) and ack and ack[0] == "driver_ack"):
                c.close()
                _time.sleep(0.5)
                continue
            c.send(("driver_store", did, shared))
        except Exception:
            _time.sleep(0.5)
            continue
        # Shared recovery (hello already exchanged above): swap, flush the
        # backlog, fail in-flight requests, replay subscriptions.  On a
        # second bounce mid-recovery, RETRY within the window — there is
        # no outer loop to re-enter here, unlike the worker recv loop.
        if rt.reconnect_recover(wire.batching(c), lambda _c: None):
            return True
        _time.sleep(0.5)
    return False


def _recv_loop(rt) -> None:
    while True:
        try:
            msg = rt.conn.recv()
        except (EOFError, OSError):
            if _try_reconnect(rt):
                continue
            # Head gone for good: fail every in-flight request instead of
            # hanging.
            err = ConnectionError("lost connection to ray_tpu head")
            for req_id, q in list(rt._pending.items()):
                rt._pending.pop(req_id, None)
                try:
                    q.put((False, err))
                except Exception:
                    pass
            return
        if msg[0] == "reply":
            rt._on_reply(msg[1], msg[2], msg[3])
        elif msg[0] == "pub":
            rt._on_pub(msg[1], msg[2], msg[3])
        # tasks are never pushed to a driver client


def detach() -> None:
    """Disconnect from the head and restore in-process driver ability."""
    global _attached
    rt = _attached
    if rt is None:
        return
    _attached = None
    rt._detaching = True  # the recv loop must not reconnect a detach
    from ray_tpu._private import refs as refs_mod
    from ray_tpu._private import runtime as runtime_mod
    from ray_tpu._private import worker_proc

    worker_proc._runtime = None
    runtime_mod._worker_mode = False
    refs_mod.set_ref_hooks(None, None)
    # The recv thread is blocked in conn.recv(); closing the fd under it
    # would free the fd number for reuse by a subsequent attach, letting
    # the old thread steal the new connection's bytes.  shutdown() the
    # socket instead (EOFs the blocked read without releasing the fd),
    # join the thread, THEN close.
    import socket as _socket

    try:
        s = _socket.socket(fileno=os.dup(rt.conn.fileno()))
        try:
            s.shutdown(_socket.SHUT_RDWR)
        finally:
            s.close()
    except OSError:
        pass
    t = getattr(rt, "_recv_thread", None)
    if t is not None:
        t.join(timeout=5)
    try:
        rt.conn.close()
    except OSError:
        pass
    if getattr(rt, "owns_store_dir", False):
        import shutil

        shutil.rmtree(rt.shm.dir, ignore_errors=True)
