"""Serialization: cloudpickle protocol-5 with out-of-band buffers.

Mirrors the reference's SerializationContext
(ray: python/ray/_private/serialization.py:92,358,438): values are pickled
with protocol 5 so large contiguous buffers (numpy / host-side jax arrays)
travel out-of-band and can be mapped zero-copy from the shared-memory store.
ObjectRefs contained inside a value are intercepted so the owner can track
borrows (ray: src/ray/core_worker/reference_count.h:61).
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle

from ray_tpu._private.refs import ObjectRef


class _RefPlaceholder:
    __slots__ = ("id", "owner")

    def __init__(self, id: str, owner: str | None):
        self.id = id
        self.owner = owner


class _Pickler(cloudpickle.Pickler):
    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)
        self.contained_refs: List[str] = []

    def persistent_id(self, obj: Any):
        if isinstance(obj, ObjectRef):
            self.contained_refs.append(obj.id)
            return ("raytpu.objectref", obj.id, obj.owner)
        return None


class _Unpickler(pickle.Unpickler):
    def __init__(self, file, *, buffers=None, ref_factory=None):
        super().__init__(file, buffers=buffers)
        self._ref_factory = ref_factory

    def persistent_load(self, pid):
        tag, id, owner = pid
        if tag != "raytpu.objectref":
            raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")
        if self._ref_factory is not None:
            return self._ref_factory(id, owner)
        return ObjectRef(id, owner)


def serialize(
    value: Any,
) -> Tuple[bytes, List[pickle.PickleBuffer], List[str]]:
    """Serialize ``value``.

    Returns (payload, out_of_band_buffers, contained_object_ref_ids).
    """
    buffers: List[pickle.PickleBuffer] = []
    f = io.BytesIO()
    p = _Pickler(f, buffers.append)
    p.dump(value)
    return f.getvalue(), buffers, p.contained_refs


def deserialize(
    payload: bytes | memoryview,
    buffers: Optional[List[memoryview]] = None,
    ref_factory: Optional[Callable[[str, str | None], ObjectRef]] = None,
) -> Any:
    u = _Unpickler(
        io.BytesIO(payload) if isinstance(payload, (bytes, bytearray)) else io.BytesIO(bytes(payload)),
        buffers=buffers,
        ref_factory=ref_factory,
    )
    return u.load()


# -- flat wire format ---------------------------------------------------------
#
# [u64 payload_len][u32 nbuf][u64 buf_len]*nbuf  then payload, then each
# buffer 64-byte aligned. Used both for inline messages and for the
# shared-memory store files so a stored object can be read back zero-copy.

import struct

_ALIGN = 64


def pack(payload: bytes, buffers: List[pickle.PickleBuffer]) -> bytearray:
    lens = [len(b.raw()) for b in buffers]
    header = struct.pack("<QI", len(payload), len(buffers)) + b"".join(
        struct.pack("<Q", n) for n in lens
    )
    out = bytearray(header)
    out += payload
    for b in buffers:
        pad = (-len(out)) % _ALIGN
        out += b"\x00" * pad
        out += b.raw()
    return out


def packed_size(payload: bytes, buffers: List[pickle.PickleBuffer]) -> int:
    n = 12 + 8 * len(buffers) + len(payload)
    for b in buffers:
        n += (-n) % _ALIGN
        n += len(b.raw())
    return n


def pack_into(mv: memoryview, payload: bytes, buffers: List[pickle.PickleBuffer]) -> None:
    """Pack directly into a writable memoryview (e.g. an mmap) without copies."""
    lens = [len(b.raw()) for b in buffers]
    off = 0
    struct.pack_into("<QI", mv, off, len(payload), len(buffers))
    off += 12
    for n in lens:
        struct.pack_into("<Q", mv, off, n)
        off += 8
    mv[off : off + len(payload)] = payload
    off += len(payload)
    for b in buffers:
        off += (-off) % _ALIGN
        raw = b.raw()
        mv[off : off + len(raw)] = raw
        off += len(raw)


def unpack(mv: memoryview) -> Tuple[memoryview, List[memoryview]]:
    payload_len, nbuf = struct.unpack_from("<QI", mv, 0)
    off = 12
    lens = []
    for _ in range(nbuf):
        (n,) = struct.unpack_from("<Q", mv, off)
        lens.append(n)
        off += 8
    payload = mv[off : off + payload_len]
    off += payload_len
    bufs = []
    for n in lens:
        off += (-off) % _ALIGN
        bufs.append(mv[off : off + n])
        off += n
    return payload, bufs
