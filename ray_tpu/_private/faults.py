"""Deterministic fault-injection plane: named, seeded injection points.

ray: the reference hardens its recovery paths with targeted testing knobs
baked into RayConfig (`testing_asio_delay_us`, `gcs_rpc_server_*` failure
injection) rather than wall-clock kill threads — a failure seen once in CI
must be re-runnable from its config.  This module is that plane for this
build: every hazard site (wire frame send/recv, peer connect/flush/
re-drive, head control delivery, pubsub publish, object-plane chunk pull,
zygote fork replies, GCS snapshot writes) calls a NAMED point, and a
one-line spec names a scenario:

    RAY_TPU_FAULT_SPEC='peer.send:drop@every=7,proc=worker' \
    RAY_TPU_FAULT_SEED=7 python my_job.py

Spec grammar (clauses joined by ';'):

    clause   := point ':' action ['@' selector (',' selector)*]
    point    := dotted name, trailing '*' wildcard ok  ("peer.*")
    action   := 'drop' | 'error' | 'crash' | 'delay=<seconds>'
    selector := 'nth=<n>'      fire only on the n-th visit (1-based)
              | 'every=<n>'    fire on every n-th visit
              | 'after=<n>'    visits <= n are never eligible
              | 'times=<m>'    fire at most m times, then the clause is spent
              | 'prob=<p>'     fire with probability p (seeded, deterministic)
              | 'at=<seconds>' eligible only once wall time since configure()
                               passes this mark (schedule anchor: "kill the
                               head at t=3s" = 'head.send:crash@at=3')
              | 'match=<s>'    fire only when the site's key contains s
                               ('^s' anchors: key must START with s — e.g.
                               match=^done hits "done" but not "pdone")
              | 'proc=<s>'     fire only in processes whose tag contains s
                               (tags: 'main', 'head', 'worker:<wid>',
                               'daemon:<node_id>', 'zygote',
                               'io_shard:<idx>'; a worker hosting an
                               actor appends ':actor:<Class>', so
                               proc=actor:Replica scopes a kill to serve
                               replicas and proc=io_shard:1 to one head
                               io shard)

Actions at the point:
    drop   -> point() returns "drop"; the site skips the operation while
              reporting success (a lost message, not a failed send);
    delay  -> point() sleeps the given seconds, then proceeds;
    error  -> point() raises InjectedFault (a ConnectionError, so sites
              that already catch OSError route it through their existing
              failure handling — the whole point);
    crash  -> SIGKILL the calling process at the point (worker/daemon/
              zygote/head process death, exactly where it hurts).

Determinism: all randomness (`prob=`) comes from a clause-local
random.Random seeded by (RAY_TPU_FAULT_SEED, point pattern, clause index),
and counter selectors are pure functions of the per-clause visit count —
the same spec + seed + visit sequence produces the same injection schedule
(asserted by tests/test_faults.py).  The fired log (`log()`) records every
injection for replay triage; the soak harness prints the seed on failure.

Overhead when unset: hazard sites guard with `if faults.ENABLED:` — a
module attribute read on the fast path, no call, no allocation.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "ENABLED",
    "InjectedFault",
    "configure",
    "disable",
    "parse_spec",
    "point",
    "log",
    "stats",
    "seed",
    "set_crash_hook",
    "set_process_tag",
]

# Module-level disabled fast path: sites check this attribute before
# calling point().  Rebound (never mutated in place) by configure/disable.
ENABLED: bool = False


class InjectedFault(ConnectionError):
    """Raised by an 'error' action.  Subclasses ConnectionError (hence
    OSError) so every site's existing failure handling treats it exactly
    like a real transport fault."""


class FaultSpecError(ValueError):
    """Spec parse failure — loud by design; a typo'd chaos plan that
    silently injects nothing would report false robustness."""


class _Clause:
    __slots__ = (
        "pattern", "action", "delay_s", "nth", "every", "after", "times",
        "prob", "at_s", "match", "proc", "rng", "visits", "fired", "lock",
    )

    def __init__(self, pattern: str, action: str, delay_s: float, index: int,
                 seed_val: int, nth: Optional[int], every: Optional[int],
                 after: int, times: Optional[int], prob: Optional[float],
                 at_s: Optional[float], match: Optional[str],
                 proc: Optional[str]):
        self.pattern = pattern
        self.action = action
        self.delay_s = delay_s
        self.nth = nth
        self.every = every
        self.after = after
        self.times = times
        self.prob = prob
        self.at_s = at_s
        self.match = match
        self.proc = proc
        # Clause-local deterministic stream: independent of every other
        # clause and of call interleaving across points.
        self.rng = random.Random(f"{seed_val}:{pattern}:{index}")
        self.visits = 0
        self.fired = 0
        self.lock = threading.Lock()

    def matches_point(self, name: str) -> bool:
        if self.pattern.endswith("*"):
            return name.startswith(self.pattern[:-1])
        return name == self.pattern

    def check(self, key: Optional[str], now_s: float) -> bool:
        """One visit; True = fire.  Counter/rng state advances under the
        clause lock so concurrent visitors see a consistent schedule."""
        if self.match is not None:
            if key is None:
                return False
            if self.match.startswith("^"):
                if not key.startswith(self.match[1:]):
                    return False
            elif self.match not in key:
                return False
        if self.proc is not None and self.proc not in _PROC_TAG:
            return False
        with self.lock:
            self.visits += 1
            v = self.visits
            if self.times is not None and self.fired >= self.times:
                return False
            if self.at_s is not None and now_s < self.at_s:
                return False
            if v <= self.after:
                return False
            if self.nth is not None and v != self.nth:
                return False
            if self.every is not None and (v - self.after) % self.every != 0:
                return False
            if self.prob is not None and self.rng.random() >= self.prob:
                return False
            self.fired += 1
            return True


_lock = threading.Lock()
_clauses: List[_Clause] = []
_seed: int = 0
_t0: float = 0.0
_spec_str: str = ""
# Fired-injection log for replay triage (bounded; soak prints it on
# failure together with the seed).
_LOG_MAX = 4096
_log: List[Tuple[float, str, str, int]] = []  # (t, point, action, visit)

# Process identity for proc= scoping.  Workers get theirs from the env
# their spawner set; zygote/daemon/head override explicitly at entry.
_PROC_TAG: str = (
    "worker:" + os.environ["RAY_TPU_WORKER_ID"]
    if os.environ.get("RAY_TPU_WORKER_ID")
    else "main"
)


def set_process_tag(tag: str) -> None:
    global _PROC_TAG
    _PROC_TAG = tag


# Pre-SIGKILL hook for 'crash' actions (telemetry.install sets the flight-
# recorder dump here): the one chance to persist what this process saw
# before the fault plane kills it.  Best-effort — a hook failure must not
# turn a deterministic crash into anything else.
_crash_hook: Optional[callable] = None


def set_crash_hook(hook) -> None:
    global _crash_hook
    _crash_hook = hook


def _parse_float(field: str, raw: str) -> float:
    try:
        return float(raw)
    except ValueError:
        raise FaultSpecError(f"fault spec: {field}={raw!r} is not a number")


def _parse_int(field: str, raw: str) -> int:
    try:
        return int(raw)
    except ValueError:
        raise FaultSpecError(f"fault spec: {field}={raw!r} is not an integer")


def _parse_clause(text: str, index: int, seed_val: int) -> _Clause:
    head, sep, selpart = text.partition("@")
    if ":" not in head:
        raise FaultSpecError(
            f"fault clause {text!r}: expected '<point>:<action>"
            f"[@sel,...]' (e.g. 'peer.send:drop@every=7')"
        )
    pattern, _, action_raw = head.partition(":")
    pattern = pattern.strip()
    action_raw = action_raw.strip()
    if not pattern:
        raise FaultSpecError(f"fault clause {text!r}: empty point name")
    delay_s = 0.0
    if action_raw.startswith("delay"):
        _, eq, secs = action_raw.partition("=")
        if not eq:
            raise FaultSpecError(
                f"fault clause {text!r}: delay needs '=<seconds>'"
            )
        delay_s = _parse_float("delay", secs)
        action = "delay"
    elif action_raw in ("drop", "error", "crash"):
        action = action_raw
    else:
        raise FaultSpecError(
            f"fault clause {text!r}: unknown action {action_raw!r} "
            "(want drop | delay=<s> | error | crash)"
        )
    nth = every = times = None
    after = 0
    prob = at_s = None
    match = proc = None
    if sep:
        for sel in selpart.split(","):
            sel = sel.strip()
            if not sel:
                continue
            k, eq, v = sel.partition("=")
            if not eq:
                raise FaultSpecError(
                    f"fault clause {text!r}: selector {sel!r} needs '=<value>'"
                )
            if k == "nth":
                nth = _parse_int(k, v)
            elif k == "every":
                every = _parse_int(k, v)
                if every <= 0:
                    raise FaultSpecError(f"fault spec: every={v} must be > 0")
            elif k == "after":
                after = _parse_int(k, v)
            elif k == "times":
                times = _parse_int(k, v)
            elif k == "prob":
                prob = _parse_float(k, v)
                if not 0.0 <= prob <= 1.0:
                    raise FaultSpecError(f"fault spec: prob={v} not in [0,1]")
            elif k == "at":
                at_s = _parse_float(k, v)
            elif k == "match":
                match = v
            elif k == "proc":
                proc = v
            else:
                raise FaultSpecError(
                    f"fault clause {text!r}: unknown selector {k!r} (want "
                    "nth|every|after|times|prob|at|match|proc)"
                )
    return _Clause(pattern, action, delay_s, index, seed_val, nth, every,
                   after, times, prob, at_s, match, proc)


def parse_spec(spec: str, seed_val: int = 0) -> List[_Clause]:
    """Parse a spec WITHOUT installing it.  The registry export: the
    concurrency lint (analysis/fault_registry.py) validates every literal
    spec in tests/scripts against the generated fault-point catalog with
    this — the real parser, so the lint can never accept a spec the
    runtime would reject.  Raises FaultSpecError on any typo."""
    return [
        _parse_clause(part.strip(), i, seed_val)
        for i, part in enumerate(spec.split(";"))
        if part.strip()
    ]


def configure(spec: str, seed_val: Optional[int] = None) -> None:
    """Parse + install a fault plan.  Raises FaultSpecError on any typo —
    never silently installs a partial plan."""
    global ENABLED, _clauses, _seed, _t0, _spec_str
    if seed_val is None:
        seed_val = _parse_int("RAY_TPU_FAULT_SEED",
                              os.environ.get("RAY_TPU_FAULT_SEED", "0") or "0")
    clauses = parse_spec(spec, seed_val)
    with _lock:
        _clauses = clauses
        _seed = seed_val
        _spec_str = spec
        _t0 = time.monotonic()
        _log.clear()
        ENABLED = bool(clauses)


def disable() -> None:
    global ENABLED, _clauses, _spec_str
    with _lock:
        _clauses = []
        _spec_str = ""
        _log.clear()
        ENABLED = False


def refresh_from_env() -> None:
    """(Re)install the plan from RAY_TPU_FAULT_SPEC / RAY_TPU_FAULT_SEED.
    Called at import (children inherit the env) and by Runtime.__init__
    (so ray_tpu.init(_system_config={'fault_spec': ...}) lands here after
    config.set_system_config exports the env form)."""
    spec = os.environ.get("RAY_TPU_FAULT_SPEC", "")
    if spec:
        configure(spec)


def seed() -> int:
    return _seed


def spec() -> str:
    return _spec_str


def point(name: str, key: Optional[str] = None) -> Optional[str]:
    """One hazard-site visit.  Returns None (proceed) or "drop" (the site
    pretends the operation happened and lost the message); raises
    InjectedFault for 'error'; sleeps for 'delay'; SIGKILLs the process
    for 'crash'.  Sites guard the call with `if faults.ENABLED:`."""
    if not ENABLED:
        return None
    now_s = time.monotonic() - _t0
    outcome: Optional[str] = None
    for c in _clauses:
        if not c.matches_point(name):
            continue
        if not c.check(key, now_s):
            continue
        with _lock:
            if len(_log) < _LOG_MAX:
                _log.append((now_s, name, c.action, c.visits))
        if c.action == "delay":
            time.sleep(c.delay_s)
        elif c.action == "crash":
            import signal

            if _crash_hook is not None:
                try:
                    _crash_hook(name)
                except Exception:
                    pass
            os.kill(os.getpid(), signal.SIGKILL)
        elif c.action == "error":
            raise InjectedFault(
                f"injected fault at {name} (visit {c.visits}, seed {_seed})"
            )
        elif c.action == "drop":
            outcome = "drop"
    return outcome


def log() -> List[Tuple[float, str, str, int]]:
    """Fired injections this configuration: (t_since_configure, point,
    action, clause_visit_index)."""
    with _lock:
        return list(_log)


def stats() -> Dict[str, int]:
    """point -> fired count (summed over clauses)."""
    out: Dict[str, int] = {}
    with _lock:
        for _t, name, _a, _v in _log:
            out[name] = out.get(name, 0) + 1
    return out


def _reset_for_tests() -> None:
    disable()


# Children (workers, daemons, zygote) inherit the spec via os.environ; the
# plan is live from this module's first import in every process.
refresh_from_env()
