"""Opt-in runtime lock watchdog — the dynamic twin of the static
concurrency lint (ray_tpu/_private/analysis/).

RAY_TPU_LOCK_WATCHDOG=1 makes the hot runtime locks (store, peer
transport, runtime, worker_proc, gcs — every make_lock() hook point)
instrumented wrappers that record, per thread, the acquisition order and
hold times the static passes can only approximate, and report:

  * ORDER INVERSIONS — lock B acquired while holding A after A was ever
    acquired while holding B (the observed-order analogue of the
    lock-order pass; TSAN's lock-order-inversion check works the same
    way: it flags the inverted ORDER even when the interleaving didn't
    deadlock this run);
  * LONG HOLDS — any lock held longer than RAY_TPU_LOCK_HOLD_S seconds
    (default 1.0; blocking I/O under a lock shows up here even when the
    blocking call is hidden behind a call chain the lexical lint can't
    see).

Reports are collected in-process (reports()) and, when
RAY_TPU_LOCK_WATCHDOG_DIR is set, appended to <dir>/<pid>.watchdog so a
multi-process harness (the chaos soak) can assert ZERO reports across
every process of the cluster.  The watchdog never raises and never
blocks: detection must not perturb the schedule it observes.

Disabled (the default), make_lock returns plain threading primitives —
zero wrappers, zero overhead.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

ENABLED: bool = os.environ.get("RAY_TPU_LOCK_WATCHDOG") == "1"


def _hold_threshold_s() -> float:
    try:
        return float(os.environ.get("RAY_TPU_LOCK_HOLD_S", "1.0"))
    except ValueError:
        return 1.0


_registry_lock = threading.Lock()  # guards the structures below only
_edges: Dict[Tuple[str, str], str] = {}  # (held, acquired) -> where first seen
_reported_pairs: Set[frozenset] = set()
_reported_holds: Set[Tuple[str, str]] = set()  # (lock, thread-name)
_reports: List[str] = []
_tls = threading.local()


def _held_stack() -> List[str]:
    try:
        return _tls.held
    except AttributeError:
        _tls.held = []
        return _tls.held


# Report hook (telemetry.install sets the flight-recorder dump here): a
# watchdog finding also dumps the process's recent-event ring, so the
# report file names WHAT inverted and the flight dump shows what the
# process was doing around it.
_report_hook = None


def set_report_hook(hook) -> None:
    global _report_hook
    _report_hook = hook


def _emit(report: str) -> None:
    with _registry_lock:
        _reports.append(report)
    out_dir = os.environ.get("RAY_TPU_LOCK_WATCHDOG_DIR")
    if out_dir:
        try:
            with open(
                os.path.join(out_dir, f"{os.getpid()}.watchdog"), "a"
            ) as f:
                f.write(report + "\n")
        except OSError:
            pass
    if _report_hook is not None:
        try:
            _report_hook(report)
        except Exception:
            pass
    import sys

    print(f"[ray_tpu] LOCK WATCHDOG: {report}", file=sys.stderr, flush=True)


def _record_acquire(name: str) -> None:
    """Called with the lock JUST acquired.  Records order edges against
    every lock this thread already holds and reports inversions."""
    held = _held_stack()
    for prior in held:
        if prior == name:
            continue
        pair = (prior, name)
        if pair not in _edges:  # racy pre-check; settled under the lock
            with _registry_lock:
                _edges.setdefault(
                    pair, threading.current_thread().name
                )
        inverse = (name, prior)
        if inverse in _edges:
            key = frozenset(pair)
            with _registry_lock:
                if key in _reported_pairs:
                    continue
                _reported_pairs.add(key)
                where = _edges[inverse]
            _emit(
                f"order inversion: acquired {name!r} while holding "
                f"{prior!r} (thread {threading.current_thread().name}), "
                f"but {prior!r} was previously acquired while holding "
                f"{name!r} (thread {where}) — potential ABBA deadlock"
            )
    held.append(name)


def _record_release(name: str, held_since: float) -> None:
    held = _held_stack()
    # Remove the innermost occurrence (non-LIFO release is legal).
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            break
    dt = time.monotonic() - held_since
    thr = _hold_threshold_s()
    if dt > thr:
        tname = threading.current_thread().name
        with _registry_lock:
            if (name, tname) in _reported_holds:
                return
            _reported_holds.add((name, tname))
        _emit(
            f"long hold: {name!r} held {dt:.3f}s (> {thr}s) by thread "
            f"{tname} — blocking work under a lock?"
        )


class _WatchedLockBase:
    """Context-manager + acquire/release surface over a real lock."""

    _inner_factory = staticmethod(threading.Lock)

    def __init__(self, name: str):
        self._name = name
        self._inner = self._inner_factory()
        # per-thread (depth, t0) for reentrant holders; plain Lock depth
        # is always 0/1
        self._holds = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            depth = getattr(self._holds, "depth", 0)
            if depth == 0:
                self._holds.t0 = time.monotonic()
                _record_acquire(self._name)
            self._holds.depth = depth + 1
        return got

    def release(self) -> None:
        depth = getattr(self._holds, "depth", 0)
        # Capture BEFORE the real release: after it another thread owns.
        t0 = getattr(self._holds, "t0", None)
        self._inner.release()
        if depth > 0:
            self._holds.depth = depth - 1
            if depth == 1 and t0 is not None:
                _record_release(self._name, t0)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __repr__(self) -> str:
        return f"<WatchedLock {self._name} {self._inner!r}>"


class WatchedLock(_WatchedLockBase):
    _inner_factory = staticmethod(threading.Lock)


class WatchedRLock(_WatchedLockBase):
    _inner_factory = staticmethod(threading.RLock)

    def _is_owned(self) -> bool:
        # RAY_TPU_DEBUG_LOCKS ownership asserts call this (runtime._locked).
        return self._inner._is_owned()


def make_lock(name: str, rlock: bool = False):
    """Hook point: construct a (possibly watched) lock.  Production pays
    one module-bool check and gets the plain primitive."""
    if not ENABLED:
        return threading.RLock() if rlock else threading.Lock()
    return WatchedRLock(name) if rlock else WatchedLock(name)


def reports() -> List[str]:
    with _registry_lock:
        return list(_reports)


def reset() -> None:
    """Test hook: clear observed edges and reports (NOT the env gate)."""
    with _registry_lock:
        _edges.clear()
        _reported_pairs.clear()
        _reported_holds.clear()
        _reports.clear()


def collect_dir_reports(out_dir: str) -> List[str]:
    """Every report written by any process into out_dir (soak harness)."""
    out: List[str] = []
    try:
        names = sorted(os.listdir(out_dir))
    except OSError:
        return out
    for fn in names:
        if not fn.endswith(".watchdog"):
            continue
        try:
            with open(os.path.join(out_dir, fn)) as f:
                out.extend(
                    f"{fn}: {line.rstrip()}" for line in f if line.strip()
                )
        except OSError:
            pass
    return out


def _enable_for_tests(enabled: bool = True) -> None:
    """Flip the gate in-process (tests); real runs use the env var so
    child processes inherit it."""
    global ENABLED
    ENABLED = enabled
