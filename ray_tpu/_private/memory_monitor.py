"""Memory monitor + worker killing policy: OOM protection for a node.

ray: src/ray/common/memory_monitor.h:52 (periodic usage check against a
usage threshold) + src/ray/raylet/worker_killing_policy.h (pick a victim
worker instead of letting the kernel OOM-kill the raylet).  Runs inside
each node daemon: a runaway task gets ITS worker killed with a retriable
out-of-memory error while the node (and every other worker) stays up.

Two accounting modes:
  * `limit_bytes` set (RAY_TPU_MEMORY_LIMIT_BYTES / _system_config):
    the node's worker-group RSS is capped at limit_bytes * threshold —
    this is also how tests drive the monitor deterministically on a
    shared machine.
  * `limit_bytes` 0: system mode — (MemTotal - MemAvailable) / MemTotal
    from /proc/meminfo against the threshold, the reference's default.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional, Tuple

_PAGE = os.sysconf("SC_PAGE_SIZE")


def process_rss_bytes(pid: int) -> int:
    """Resident set of one process via /proc/<pid>/statm (no psutil)."""
    try:
        with open(f"/proc/{pid}/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


def system_memory() -> Tuple[int, int]:
    """(used_bytes, total_bytes) from /proc/meminfo, kernel's own
    MemAvailable estimate (ray: memory_monitor.cc GetLinuxMemoryBytes)."""
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total and avail:
                    break
    except OSError:
        return 0, 0
    return total - avail, total


def choose_victim(
    workers: Dict[str, Tuple[int, float]], policy: str = "largest"
) -> Optional[str]:
    """Worker killing policy. `workers`: wid -> (rss_bytes, spawn_ts).

    "largest" (default): kill the biggest RSS — under group pressure that
    is the actual hog, never an idle pool worker.
    "newest": kill the most recently spawned worker — least sunk work
    (ray: retriable-FIFO ordering in worker_killing_policy.cc).
    """
    if not workers:
        return None
    if policy == "newest":
        return max(workers.items(), key=lambda kv: kv[1][1])[0]
    return max(workers.items(), key=lambda kv: kv[1][0])[0]


class MemoryMonitor:
    """Background thread: check usage every `interval_s`, kill ONE victim
    per breach via `kill_cb(wid, rss, used, limit)`, then hold a cooldown
    (4x interval, >=1s) so the kernel reclaims the victim's pages before
    the next verdict — without it a single pressure spike triggers a kill
    per beat.

    System-mode caveat: /proc/meminfo is HOST-wide, so the deployment
    assumption is one monitoring daemon per host (the reference's shape —
    one raylet per node).  Test clusters that co-host several daemons on
    one machine should set memory_limit_bytes for per-group accounting,
    where monitors are independent by construction."""

    def __init__(
        self,
        get_workers: Callable[[], Dict[str, Tuple[int, float]]],
        kill_cb: Callable[[str, int, int, int], None],
        *,
        limit_bytes: int = 0,
        threshold: float = 0.95,
        interval_s: float = 0.25,
        policy: str = "largest",
    ):
        self._get_workers = get_workers
        self._kill_cb = kill_cb
        self.limit_bytes = limit_bytes
        self.threshold = threshold
        self.interval_s = interval_s
        self.policy = policy
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="memory-monitor"
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _usage(self, workers) -> Tuple[int, int]:
        """(used, limit) in the active accounting mode."""
        if self.limit_bytes > 0:
            used = sum(rss for rss, _ts in workers.values())
            return used, int(self.limit_bytes * self.threshold)
        used, total = system_memory()
        return used, int(total * self.threshold) if total else (1 << 62)

    def check_once(self) -> Optional[str]:
        """One monitor beat; returns the killed wid (for tests)."""
        workers = {
            wid: (process_rss_bytes(pid), ts)
            for wid, (pid, ts) in self._get_workers().items()
        }
        used, limit = self._usage(workers)
        if used <= limit:
            return None
        victim = choose_victim(workers, self.policy)
        if victim is None:
            return None
        self._kill_cb(victim, workers[victim][0], used, limit)
        return victim

    def _loop(self) -> None:
        cooldown = max(1.0, 4 * self.interval_s)
        while not self._stop.wait(self.interval_s):
            try:
                killed = self.check_once()
            except Exception:
                killed = None  # monitoring must never take the daemon down
            if killed is not None and self._stop.wait(cooldown):
                return
