"""ObjectRef: a first-class future naming an immutable object in the store.

Mirrors the semantics of the reference's ObjectRef/ObjectID
(ray: python/ray/includes/object_ref.pxi, src/ray/common/id.h): the ref is
ownership-aware (the driver/worker that created the producing task owns the
value's lifetime metadata) and refcounted -- dropping the last Python reference
releases the underlying object (ray: src/ray/core_worker/reference_count.h:61).
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Any, Callable, Optional

# Process-local hook installed by the runtime so that ObjectRef GC can
# decrement the owner-side reference count. Kept as a module global to avoid
# import cycles.
_release_hook: Optional[Callable[[str], None]] = None
_addref_hook: Optional[Callable[[str], None]] = None

# Releases are DEFERRED out of __del__: GC runs at arbitrary allocation
# points — including while the current thread holds the transport or wire
# locks the release hooks themselves take (DirectTransport.decref /
# oneway's conn lock).  A synchronous hook there is a self-deadlock on a
# plain lock and an ABBA inversion otherwise (the chaos soak's lock
# watchdog caught exactly this under batch-flush allocation pressure).
# __del__ therefore only appends to a GIL-atomic deque; a tiny daemon
# thread drains it in FIFO order.  Guard ADDS stay synchronous, so the
# "add before any later del" ordering the ownership protocol needs is
# unchanged — dels only ever get later, which is always safe.
_pending_releases: "collections.deque[str]" = collections.deque()
_release_event = threading.Event()
_drainer_lock = threading.Lock()
_drainer_pid: Optional[int] = None

# ---------------------------------------------------------------------------
# Live-ref table: this process's leg of the cluster object ledger
# (telemetry.py ObjectLedger; ray: reference_count.h:61 keeps exactly this
# per-worker table and `ray memory` joins them).  Every ObjectRef
# construction registers {oid: count} here (plus, when RAY_TPU_REF_CALLSITE
# is on, the first non-ray_tpu creation site); __del__ queues a GIL-atomic
# decrement (same no-locks-in-GC rule as the release queue above).  The
# worker/driver telemetry tick snapshots the table and ships it head-ward
# as a droppable refs_push oneway.

_table_lock = threading.Lock()
_table_pid: Optional[int] = None
_live_table: dict = {}  # oid -> live ObjectRef count in this process
_ref_sites: dict = {}  # oid -> "file.py:line" creation site (knob-gated)
_table_dels: "collections.deque[str]" = collections.deque()


def _callsite() -> Optional[str]:
    """First stack frame outside the ray_tpu package — the user line that
    created the ref.  Only called when the ref_callsite knob is on."""
    import sys as _sys

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    f = _sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(pkg):
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return None


def _table_check_pid_locked() -> None:
    # After a fork the inherited table describes the PARENT's refs.
    global _table_pid
    if _table_pid != os.getpid():
        _table_pid = os.getpid()
        _live_table.clear()
        _ref_sites.clear()
        _table_dels.clear()


def _drain_table_dels_locked() -> None:
    while True:
        try:
            oid = _table_dels.popleft()
        except IndexError:
            return
        c = _live_table.get(oid, 0) - 1
        if c > 0:
            _live_table[oid] = c
        else:
            _live_table.pop(oid, None)
            _ref_sites.pop(oid, None)


def _table_note_new(oid: str) -> None:
    site = None
    try:
        from ray_tpu._private import config as _config

        if _config.get("ref_callsite"):
            site = _callsite()
    except Exception:
        pass
    with _table_lock:
        _table_check_pid_locked()
        if len(_table_dels) > 512:  # keep the GC queue bounded
            _drain_table_dels_locked()
        _live_table[oid] = _live_table.get(oid, 0) + 1
        if site is not None and oid not in _ref_sites:
            _ref_sites[oid] = site


def snapshot_refs(limit: int = 4096) -> dict:
    """{oid: [count, site|None]} for every live ObjectRef here, plus a
    truncation marker — the refs_push payload body."""
    with _table_lock:
        _table_check_pid_locked()
        _drain_table_dels_locked()
        refs = {}
        for oid, n in _live_table.items():
            if len(refs) >= limit:
                break
            refs[oid] = [n, _ref_sites.get(oid)]
        truncated = len(_live_table) > len(refs)
    return {"refs": refs, "truncated": truncated}


def _reset_table_for_tests() -> None:
    global _table_pid
    with _table_lock:
        _table_pid = None
        _live_table.clear()
        _ref_sites.clear()
        _table_dels.clear()


def _drain_releases() -> None:
    import time as _time

    while True:
        _release_event.wait()
        # Let a burst accumulate before draining: releases are not
        # latency-critical, and waking per-ref would turn a put/task loop
        # into a context-switch storm on small hosts.
        _time.sleep(0.001)
        _release_event.clear()
        while True:
            try:
                oid = _pending_releases.popleft()
            except IndexError:
                break
            hook = _release_hook
            if hook is None:
                continue  # hooks uninstalled (shutdown): drop, as before
            try:
                hook(oid)
            except Exception:
                pass
        # Fold queued __del__ decrements into the live-ref table on the
        # same cadence (normal thread context: locks are safe here).
        try:
            with _table_lock:
                _drain_table_dels_locked()
        except Exception:
            pass


def _ensure_drainer() -> None:
    """Start (or, after a fork, restart) the release drainer.  Called from
    set_ref_hooks — normal context, never from __del__."""
    global _drainer_pid
    with _drainer_lock:
        if _drainer_pid == os.getpid():
            return
        _drainer_pid = os.getpid()
        _pending_releases.clear()  # a forked parent's queue is not ours
        threading.Thread(
            target=_drain_releases, daemon=True, name="raytpu-ref-release"
        ).start()


def set_ref_hooks(addref, release) -> None:
    global _release_hook, _addref_hook
    _addref_hook = addref
    _release_hook = release
    if release is not None:
        _ensure_drainer()


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, id: str, owner: str | None = None, *, _count: bool = True):
        self._id = id
        self._owner = owner
        _table_note_new(id)
        if _count and _addref_hook is not None:
            _addref_hook(id)

    def hex(self) -> str:
        return self._id

    @property
    def id(self) -> str:
        return self._id

    @property
    def owner(self) -> str | None:
        return self._owner

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __repr__(self) -> str:
        return f"ObjectRef({self._id})"

    def __del__(self):
        # Never call the hook (or take the table lock) here: __del__ runs
        # at arbitrary GC points, possibly while THIS thread holds the very
        # locks the hook takes.  Queue everything for the drainer thread —
        # deque appends are GIL-atomic.
        try:
            _table_dels.append(self._id)
        except Exception:
            pass
        if _release_hook is not None:
            try:
                _pending_releases.append(self._id)
                if not _release_event.is_set():  # one wake per burst
                    _release_event.set()
            except Exception:
                pass

    def __reduce__(self):
        # Plain pickling (outside the runtime's serialization context) loses
        # the refcount borrow; the runtime's SerializationContext intercepts
        # ObjectRefs before pickle ever sees them (see serialization.py).
        return (ObjectRef, (self._id, self._owner))

    # Allow `await ref` anywhere async code runs — the driver, async
    # actors, serve replicas, attached drivers (the async handle API rides
    # on this: `await handle.remote(...)`).
    def __await__(self):
        import asyncio

        from ray_tpu._private.client import client

        loop = asyncio.get_running_loop()
        return loop.run_in_executor(None, client.get, self).__await__()
