"""ObjectRef: a first-class future naming an immutable object in the store.

Mirrors the semantics of the reference's ObjectRef/ObjectID
(ray: python/ray/includes/object_ref.pxi, src/ray/common/id.h): the ref is
ownership-aware (the driver/worker that created the producing task owns the
value's lifetime metadata) and refcounted -- dropping the last Python reference
releases the underlying object (ray: src/ray/core_worker/reference_count.h:61).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

# Process-local hook installed by the runtime so that ObjectRef GC can
# decrement the owner-side reference count. Kept as a module global to avoid
# import cycles.
_release_hook: Optional[Callable[[str], None]] = None
_addref_hook: Optional[Callable[[str], None]] = None


def set_ref_hooks(addref, release) -> None:
    global _release_hook, _addref_hook
    _addref_hook = addref
    _release_hook = release


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, id: str, owner: str | None = None, *, _count: bool = True):
        self._id = id
        self._owner = owner
        if _count and _addref_hook is not None:
            _addref_hook(id)

    def hex(self) -> str:
        return self._id

    @property
    def id(self) -> str:
        return self._id

    @property
    def owner(self) -> str | None:
        return self._owner

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __repr__(self) -> str:
        return f"ObjectRef({self._id})"

    def __del__(self):
        if _release_hook is not None:
            try:
                _release_hook(self._id)
            except Exception:
                pass

    def __reduce__(self):
        # Plain pickling (outside the runtime's serialization context) loses
        # the refcount borrow; the runtime's SerializationContext intercepts
        # ObjectRefs before pickle ever sees them (see serialization.py).
        return (ObjectRef, (self._id, self._owner))

    # Allow `await ref` anywhere async code runs — the driver, async
    # actors, serve replicas, attached drivers (the async handle API rides
    # on this: `await handle.remote(...)`).
    def __await__(self):
        import asyncio

        from ray_tpu._private.client import client

        loop = asyncio.get_running_loop()
        return loop.run_in_executor(None, client.get, self).__await__()
