"""Worker process: task execution loop.

Analogue of the reference's worker main
(ray: python/ray/_private/workers/default_worker.py entering
CoreWorkerProcess::RunTaskExecutionLoop, python/ray/_raylet.pyx:1600) and the
executor-side scheduling queues
(ray: src/ray/core_worker/transport/actor_scheduling_queue.h et al.):

  * a recv thread demultiplexes driver messages (tasks, replies, kill);
  * an executor runs tasks -- single-threaded FIFO for plain tasks and
    default actors (ordered, like ActorSchedulingQueue), a thread pool for
    max_concurrency>1 (OutOfOrderActorSchedulingQueue), and a persistent
    asyncio loop for async actors (ray: concurrency_group_manager.h/fiber.h);
  * large results are written straight into the host shm store (zero-copy
    hand-off to the owner, like plasma Seal) -- only metadata rides the
    control connection.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time

from ray_tpu._private import lock_watchdog
import traceback
from collections import OrderedDict
from typing import Any, Dict, Optional

from ray_tpu._private import serialization as ser
from ray_tpu._private.store import ShmStore, inline_threshold
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu.exceptions import TaskError


class WorkerRuntime:
    """The in-worker runtime: proxies API calls to the owner/driver.

    Plays the role of the reference's CoreWorker as linked into a worker
    process (ray: src/ray/core_worker/core_worker.h:284) -- get/put/submit
    flow back to the owner over the control connection, except shm reads
    which go straight to tmpfs.
    """

    def __init__(self, conn, conn_lock, session_name: str, worker_id: str,
                 authkey: bytes = b"", store_dir: Optional[str] = None):
        self.conn = conn
        self.conn_lock = conn_lock
        self.worker_id = worker_id
        self.authkey = authkey
        # Direct worker<->worker transport (peer.py): installed by
        # worker_main after the peer server binds.  None only in tests
        # that construct a bare WorkerRuntime.
        self.direct = None
        self._puts_unacked = 0
        self._puts_lock = lock_watchdog.make_lock("WorkerRuntime._puts_lock")  # max_concurrency>1 puts race
        # RAY_TPU_STORE_DIR scopes the store to THIS worker's node (set by
        # its node daemon); without it (head-node workers) the session
        # default resolves to the head store.  Objects on other nodes are
        # never path-reachable — they arrive via the transfer plane.
        self.shm = ShmStore(
            session_name,
            dir_path=store_dir or os.environ.get("RAY_TPU_STORE_DIR"),
        )
        self.session_name = session_name
        # Guards _pulls_inflight only (held for dict ops, never across
        # the wire): oid -> Event of the in-flight leader pull.
        self._pull_lock = lock_watchdog.make_lock("WorkerRuntime._pull_lock")
        self._pulls_inflight: Dict[str, Any] = {}
        # Remote (non-co-located) drivers cannot seal into any node store
        # the cluster can read: their puts always ride the control conn.
        self.force_inline_puts = False
        self._req_counter = 0
        self._req_lock = lock_watchdog.make_lock("WorkerRuntime._req_lock")
        self._pending: Dict[int, queue.Queue] = {}
        self._fn_cache: Dict[str, Any] = {}
        self.current_actor = None  # instance, when this worker hosts an actor
        self.current_actor_id: Optional[str] = None
        # Creation TaskSpec of the hosted actor: re-announced with the
        # reconnect hello so a restarted head can rebuild the actor record
        # even when its journal was lost (reconciliation handshake).
        self.current_actor_spec = None
        # Batched task-event reporter (installed by worker_main): the
        # direct transport records lease-dispatch RUNNING events here.
        self.task_event_sink = None
        # Relayed tasks received but not yet replied (queued + executing):
        # the reconnect hello announces these so the head can re-drive
        # exactly what the dead conn lost — a task push that never
        # arrived, or a done frame that died in the socket (an io-shard
        # death loses both shapes while this process lives on).  Dict ops
        # are GIL-atomic; insertion order mirrors arrival order.
        self.relayed_pending: Dict[str, None] = {}
        # Oneways that failed during a head bounce, flushed on reconnect.
        self._oneway_backlog: list = []
        self._backlog_lock = lock_watchdog.make_lock("WorkerRuntime._backlog_lock")
        self._backlog_dropped = 0
        # Bumped by every SUCCESSFUL reconnect_recover: request() retries
        # use it to tell a healed-then-rebroken conn (fresh incident,
        # fresh window) from one continuous outage (budget runs out).
        self._conn_generation = 0
        # Attached drivers adopt the head's window (their own env may not
        # carry the knob); None = read the local config.
        self.reconnect_window_override: Optional[float] = None
        # Cross-process pubsub subscriptions: (channel, key) -> [cb].
        self._subs: Dict[tuple, list] = {}
        self._subs_lock = lock_watchdog.make_lock("WorkerRuntime._subs_lock")
        # Objects THIS process has seen materialized (resolved a value /
        # pulled a copy): a dep in this set is provably produced, so a
        # lease-dispatched task carrying it can be pushed — the executor
        # stages the bytes via the transfer plane without any deadlock
        # risk (the producer is done; nothing is starved).  Bounded LRU.
        self._known_ready: "OrderedDict[str, bool]" = OrderedDict()
        self._known_ready_lock = lock_watchdog.make_lock("WorkerRuntime._known_ready_lock")
        self.async_loop = None
        self._async_loop_lock = lock_watchdog.make_lock("WorkerRuntime._async_loop_lock")

    # -- request/reply to driver --------------------------------------------

    def _reconnect_window(self) -> float:
        if self.reconnect_window_override is not None:
            return self.reconnect_window_override
        from ray_tpu._private import config as _config

        return _config.get("reconnect_window_s")

    def request(self, op: str, payload: Any, timeout: Optional[float] = None) -> Any:
        """Request/reply to the owner.  In head-split mode a request that
        dies with the head conn is RE-SENT on the reconnected one (the
        restarted head's ops are idempotent by task/actor id), so a get()
        blocked across a head bounce resolves instead of erroring —
        ray: gcs_failover_worker_reconnect_timeout semantics."""
        import time as _time

        deadline = None
        last_err = None
        gen_at_err = None
        while True:
            try:
                return self._request_once(op, payload, timeout)
            except ConnectionError as e:
                window = self._reconnect_window()
                if window <= 0:
                    raise  # classic mode: conn loss is final
                now = _time.monotonic()
                # A fresh INCIDENT gets a fresh budget.  Two signals mark
                # one: a successful reconnect happened since the last
                # failure (the conn GENERATION moved — each head bounce
                # that heals must not eat into the next bounce's window;
                # a long-lived parked get that rides bounce after bounce
                # spaced under the window would otherwise accumulate into
                # a spurious give-up), or the last failure is simply old.
                gen = getattr(self, "_conn_generation", 0)
                if (
                    last_err is None
                    or gen != gen_at_err
                    or now - last_err > window + 10.0
                ):
                    deadline = now + window + 10.0
                gen_at_err = gen
                last_err = now
                if now > deadline:
                    # Say WHICH budget lapsed — "connection reset" alone
                    # reads like a missing retry, not an exhausted one.
                    raise ConnectionError(
                        f"request {op!r} still failing after riding the "
                        f"{window:.0f}s reconnect window: {e}"
                    ) from e
                _time.sleep(0.2)  # recv thread is swapping the conn

    def _request_once(self, op: str, payload: Any, timeout: Optional[float]) -> Any:
        from ray_tpu._private import wire as _wire

        with self._req_lock:
            self._req_counter += 1
            req_id = self._req_counter
            q: queue.Queue = queue.Queue(1)
            self._pending[req_id] = q
        try:
            with self.conn_lock:
                self.conn.send(("req", req_id, op, payload))
            # Flush-before-blocking-wait: the req (and every oneway
            # coalesced ahead of it — refops, seals) goes out as one
            # physical write before this thread parks on the reply.
            _wire.flush_conn(self.conn)
        except OSError as e:
            self._pending.pop(req_id, None)
            raise ConnectionError("head connection lost mid-send") from e
        ok, value = q.get(timeout=timeout)
        if not ok:
            raise value
        return value

    def oneway(self, msg: tuple, droppable: bool = False) -> None:
        """droppable=True marks telemetry (spans, task events): dropped on
        a dead conn instead of competing with seals/refops for the
        bounded ownership backlog."""
        with self.conn_lock:
            try:
                self.conn.send(msg)
            except OSError:
                if droppable:
                    return
                # Head away (restart window): hold the message — seals,
                # refops, and promotions carry ownership state the
                # restarted head must still learn.  Appended INSIDE the
                # conn_lock hold: the reconnect flush (also under
                # conn_lock) can't interleave, so a failed send can never
                # strand its message behind an already-finished flush.
                if self._reconnect_window() > 0:
                    with self._backlog_lock:
                        if len(self._oneway_backlog) < 4096:
                            self._oneway_backlog.append(msg)
                        else:
                            # Overflow is ownership-state LOSS: say so
                            # (once per burst) instead of silently eating
                            # seals/refops the restarted head needed.
                            self._backlog_dropped += 1
                            if self._backlog_dropped == 1:
                                print(
                                    "[ray_tpu] head-bounce backlog full: "
                                    "dropping control messages (seals/"
                                    "refops) — objects produced during "
                                    "this outage may be unresolvable",
                                    file=sys.stderr,
                                    flush=True,
                                )

    def _on_reply(self, req_id: int, ok: bool, value: Any) -> None:
        q = self._pending.pop(req_id, None)
        if q is not None:
            q.put((ok, value))

    # -- cross-process pubsub (pubsub.py remote delivery) --------------------

    def subscribe(self, channel: str, key, cb, once: bool = False) -> None:
        """Receive pushes for (channel, key) from the head's Publisher —
        key "*" = every key on the channel.  One head message per
        subscription, then events arrive push-style on this conn (no
        round trip per event; ray: subscriber.h:70).  once=True drops the
        subscription — on BOTH sides — after the first event (per-object
        channels like object_ready would otherwise accumulate forever)."""
        with self._subs_lock:
            self._subs.setdefault((channel, key), []).append((cb, once))
        self.oneway(("subscribe", channel, key, once))

    def unsubscribe(self, channel: str, key, cb=None) -> None:
        with self._subs_lock:
            lst = self._subs.get((channel, key))
            if lst is not None:
                if cb is None:
                    lst.clear()
                else:
                    lst[:] = [e for e in lst if e[0] is not cb]
                if not lst:
                    self._subs.pop((channel, key), None)
        self.oneway(("unsubscribe", channel, key))

    def _on_pub(self, channel: str, key, args: tuple) -> None:
        with self._subs_lock:
            exact = self._subs.get((channel, key), [])
            # key == "*" would alias `wild` to `exact` (double-fire +
            # double-consume); pub frames carry concrete keys, but guard.
            wild = self._subs.get((channel, "*"), []) if key != "*" else []
            fired = list(exact) + list(wild)
            # Consume once-subs from BOTH registries: a once+wildcard sub
            # fired here and must not fire on every later key forever.
            exact[:] = [e for e in exact if not e[1]]
            if not exact:
                self._subs.pop((channel, key), None)
            if key != "*":
                wild[:] = [e for e in wild if not e[1]]
                if not wild:
                    self._subs.pop((channel, "*"), None)
        for cb, _once in fired:
            try:
                cb(key, *args)
            except Exception:
                import traceback

                traceback.print_exc()

    def reconnect_recover(self, newconn, send_hello) -> bool:
        """ONE implementation of post-bounce session recovery (worker AND
        attached-driver reconnects): swap to the freshly-connected conn,
        send the re-registration hello, flush the oneway backlog (unsent
        tail restored on a second bounce), fail in-flight requests with
        the retriable ConnectionError, replay promotions + subscriptions.
        Returns False when the head bounced again mid-recovery (caller
        retries within its window)."""
        from ray_tpu._private import wire as _wire

        with self.conn_lock:
            # Frames the dead conn queued but never flushed (a batch flush
            # failing marks the conn broken and strands its pending run)
            # carry the same ownership state the backlog does — and they
            # are OLDER, so they replay first.  Replayed as RAW bodies:
            # unpickling here would run ObjectRef refcount hooks (transport
            # lock) under this conn lock — the watchdog-caught ABBA shape.
            stranded = getattr(self.conn, "drain_pending_bodies", lambda: [])()
            try:
                self.conn.close()
            except OSError:
                pass
            self.conn = newconn
            try:
                send_hello(newconn)
                _wire.flush_conn(newconn)
            except OSError:
                return False
            with self._backlog_lock:
                backlog, self._oneway_backlog = self._oneway_backlog, []
            try:
                while stranded:
                    newconn.send_body(stranded[0])
                    stranded.pop(0)
                while backlog:
                    newconn.send(backlog[0])
                    backlog.pop(0)
                _wire.flush_conn(newconn)
            except OSError:
                # Unsent tail goes back: ownership state must survive
                # repeated bounces.
                with self._backlog_lock:
                    self._oneway_backlog[:0] = backlog
                return False
            self._backlog_dropped = 0  # fresh overflow warning per burst
            # The swap succeeded: failures after this are a NEW incident.
            self._conn_generation = getattr(self, "_conn_generation", 0) + 1
        err = ConnectionError("head connection was reset (head restart)")
        for req_id in list(self._pending):
            q = self._pending.pop(req_id, None)
            if q is not None:
                q.put((False, err))
        if self.direct is not None:
            self.direct.replay_promotions()
            # Reconciliation handshake, caller leg: re-announce the direct
            # actor routes this process holds so the restarted head can
            # cross-check its rebuilt actor table (the hosting worker's
            # own hello carries the authoritative record).
            self.direct.announce_routes()
        self._replay_subscriptions()
        return True

    def actor_announcement(self):
        """Reconciliation payload for the reconnect hello: the live actor
        this worker hosts, creation spec included, so a restarted head can
        rebuild the record even when its journal was lost (None for
        stateless workers)."""
        if self.current_actor_id is None:
            return None
        return {
            "actor_id": self.current_actor_id,
            "creation_spec": self.current_actor_spec,
        }

    def _replay_subscriptions(self) -> None:
        """After a head bounce: the restarted head's registry is empty."""
        with self._subs_lock:
            entries = [
                (ck, all(once for _cb, once in lst))
                for ck, lst in self._subs.items()
                if lst
            ]
        for (channel, key), once in entries:
            self.oneway(("subscribe", channel, key, once))

    # -- object plane --------------------------------------------------------

    def ref_factory(self, id: str, owner: str | None):
        from ray_tpu._private.refs import ObjectRef

        return ObjectRef(id, owner)  # hooks installed in worker_main count it

    def borrow_ref(self, oid: str) -> None:
        """Add one reference on behalf of an in-flight direct call's args
        (released by unborrow_ref when the call completes)."""
        if self.direct is not None and self.direct.addref(oid):
            return
        self.oneway(("refop", "add", oid))

    def unborrow_ref(self, oid: str) -> None:
        if self.direct is not None and self.direct.decref(oid):
            return
        self.oneway(("refop", "del", oid))

    def ref_table_snapshot(self) -> dict:
        """This process's live-ref table (refs.py) with direct-transport
        ownership folded in — the refs_push payload (the worker leg of the
        cluster object ledger, telemetry.py ObjectLedger)."""
        import time as _time

        from ray_tpu._private import refs as refs_mod

        snap = refs_mod.snapshot_refs()
        owned: set = set()
        pinned: set = set()
        if self.direct is not None:
            with self.direct.lock:
                owned = set(self.direct.counts)
                pinned = {
                    oid
                    for oid, dr in self.direct.results.items()
                    if dr.event.is_set()
                }
        refs = {}
        for oid, rec in snap["refs"].items():
            refs[oid] = [rec[0], rec[1], oid in owned, oid in pinned]
        for oid in owned - set(refs):
            # Owned results whose caller-side ObjectRef is pre-counted
            # (constructed with _count=False before the table existed, or
            # held only by the transport cache) still belong in the table.
            refs[oid] = [1, None, True, oid in pinned]
        snap["refs"] = refs
        snap["pid"] = os.getpid()
        snap["t"] = _time.time()
        return snap

    def note_escaped(self, contained) -> None:
        """Serialize-time hook: any locally-owned direct result leaving this
        process must become visible to the head (promotion) so remote
        consumers can resolve it."""
        if self.direct is None or not contained:
            return
        for oid in contained:
            self.direct.mark_escaped(oid)

    def mark_known_ready(self, oid: str) -> None:
        with self._known_ready_lock:
            self._known_ready[oid] = True
            self._known_ready.move_to_end(oid)
            while len(self._known_ready) > 8192:
                self._known_ready.popitem(last=False)

    def known_materialized(self, oid: str) -> bool:
        """This process has direct evidence the object was produced (seen
        its value, or it sits in this node's store)."""
        with self._known_ready_lock:
            if oid in self._known_ready:
                return True
        return self.shm.contains(oid)

    def get_value(self, object_id: str, timeout: Optional[float] = None) -> Any:
        from ray_tpu.exceptions import ObjectLostError

        try:
            value = self._get_value(object_id, timeout)
        except ObjectLostError:
            # Invalidate: a stale known-ready entry would keep steering
            # lease-path submits at a dep whose bytes are gone (the
            # deadlock guard must see the loss, not the old success).
            with self._known_ready_lock:
                self._known_ready.pop(object_id, None)
            raise
        self.mark_known_ready(object_id)  # reached only on success
        return value

    def _get_value(self, object_id: str, timeout: Optional[float] = None) -> Any:
        # Fastest path: a result of one of OUR direct calls, cached locally.
        if self.direct is not None:
            if self.direct.ready_local(object_id) is not None:
                found, val = self.direct.get_local(object_id, timeout)
                if found:
                    return val
                # shm result on a remote node: resolve via the owner below.
        # Fast path: sealed segment already in this NODE's store.
        obj = self.shm.get(object_id)
        if obj is not None:
            return obj.deserialize(self.ref_factory)
        # The owner may spill the segment between its ("shm", None) reply
        # and our mmap; re-requesting makes the owner restore it from the
        # spill file (or reconstruct via lineage) — so a miss here is a
        # retry, not a loss.  One deadline covers all retries.
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        for _ in range(3):
            remaining = (
                None if deadline is None else max(deadline - _time.monotonic(), 0.0)
            )
            import queue as _q

            try:
                kind, data = self.request("get_object", object_id, timeout=remaining)
            except _q.Empty:
                from ray_tpu.exceptions import GetTimeoutError

                raise GetTimeoutError(f"get({object_id}) timed out")
            if kind == "inline":
                payload, bufs = ser.unpack(memoryview(data))
                return ser.deserialize(payload, bufs, self.ref_factory)
            if kind == "pull":
                remaining = (
                    None
                    if deadline is None
                    else max(deadline - _time.monotonic(), 0.01)
                )
                obj = self._pull(object_id, data, remaining)
                if obj is not None:
                    return obj.deserialize(self.ref_factory)
                continue  # every endpoint failed: re-ask the owner
            # kind == "shm": on this node's store
            obj = self.shm.get(object_id)
            if obj is not None:
                return obj.deserialize(self.ref_factory)
        from ray_tpu.exceptions import ObjectLostError

        raise ObjectLostError(object_id)

    def _pull(self, object_id: str, endpoints, timeout: Optional[float] = None):
        """Fetch a remote copy into this node's store via the transfer
        plane; one pull at a time per worker (pull-manager-style admission
        — concurrent arg resolutions of the same object would race the
        allocate anyway).  The endpoint list is the owner's TRANSFER PLAN:
        assigned feed first (possibly a mid-flight relay), sealed sources
        as fallback.  This pull's own board makes the node a relay feed
        the moment bytes start landing.  `timeout` carries the caller's
        remaining get() budget so a user timeout is honored over the
        transfer default."""
        from ray_tpu._private import config as _cfg
        from ray_tpu._private.object_plane import pull_from_any

        import threading as _threading

        cap = _cfg.get("object_transfer_timeout_s")
        timeout = cap if timeout is None else min(timeout, cap)
        # Per-OBJECT dedup instead of one worker-wide pull lock: pulls of
        # DIFFERENT objects run concurrently (multi-arg resolution
        # overlaps its transfers), while a second thread wanting the SAME
        # object parks on the leader's event — and no lock is ever held
        # across the wire (the old whole-pull lock showed up as multi-
        # second watchdog holds once relays made long transfers common).
        with self._pull_lock:
            evt = self._pulls_inflight.get(object_id)
            leader = evt is None
            if leader:
                evt = _threading.Event()
                self._pulls_inflight[object_id] = evt
        if not leader:
            evt.wait(timeout)
            return self.shm.get(object_id)
        try:
            obj = self.shm.get(object_id)  # a sibling pull may have landed it
            if obj is not None:
                return obj
            r = pull_from_any(
                endpoints, self.authkey, object_id,
                self.shm.start_pull,
                timeout=timeout,
            )
            if r is None:
                return None
            n, via = r
            # Report the new copy (with its packed size + transfer path)
            # so the directory serves this node locally from now on,
            # releases the plan slot, deletes the copy when the object is
            # freed, and — for head-node workers — enters it in the owner
            # store's capacity accounting.  A "local" landing (sibling
            # sealed it under us) moved no bytes and reports nothing.
            if via != "local":
                self.oneway(("object_copied", object_id, n, via))
            return self.shm.get(object_id)
        finally:
            with self._pull_lock:
                self._pulls_inflight.pop(object_id, None)
            evt.set()

    def put_value(self, value: Any) -> str:
        """Store a value under a locally-minted id with fire-and-forget
        sealing (the owner learns of it via a oneway riding the same FIFO
        conn as every later message naming the id — so a submit carrying
        the ref always lands after the seal).  A sync request every 64
        unacked puts bounds the backlog a put-loop can build up (the
        backpressure the old request-per-put path provided implicitly)."""
        from ray_tpu._private import ids as _ids

        payload, buffers, contained = ser.serialize(value)
        self.note_escaped(contained)
        size = len(payload) + sum(len(b.raw()) for b in buffers)
        oid = _ids.object_id()
        if size >= inline_threshold() and not self.force_inline_puts:
            packed = self.shm.create(oid, payload, buffers)
            from ray_tpu._private import telemetry as _telemetry

            _telemetry.count_copy("seal", packed)
            self.oneway(("seal_ow", oid, packed, contained))
        else:
            self.oneway(("put_ow", oid, bytes(ser.pack(payload, buffers)), contained))
        with self._puts_lock:
            self._puts_unacked += 1
            flush = self._puts_unacked >= 64
            if flush:
                self._puts_unacked = 0
        if flush:
            self.request("sync", None)
        return oid

    # -- function resolution -------------------------------------------------

    def resolve_function(self, fn_id: str, blob: Optional[bytes]):
        fn = self._fn_cache.get(fn_id)
        if fn is None:
            if blob is None:
                blob = self.request("get_function", fn_id)
            import cloudpickle

            fn = cloudpickle.loads(blob)
            self._fn_cache[fn_id] = fn
        return fn


_runtime: Optional[WorkerRuntime] = None

# Currently-executing task id, tracked with a ContextVar: isolated per
# thread (FIFO / pool executors) AND per asyncio task (async actors run
# interleaved coroutines on one loop thread, where a thread-local would
# bleed between concurrent requests).  Submissions made INSIDE a task read
# this to stamp their parent for trace trees.
import contextvars

_current_task: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "raytpu_current_task", default=None
)


def current_task_id() -> Optional[str]:
    return _current_task.get()


def get_worker_runtime() -> Optional[WorkerRuntime]:
    return _runtime


def _resolve_args(rt: WorkerRuntime, args_blob: bytes):
    from ray_tpu._private.refs import ObjectRef

    payload, bufs = ser.unpack(memoryview(args_blob))
    args, kwargs = ser.deserialize(payload, bufs, rt.ref_factory)
    args = tuple(rt.get_value(a.id) if isinstance(a, ObjectRef) else a for a in args)
    kwargs = {
        k: rt.get_value(v.id) if isinstance(v, ObjectRef) else v for k, v in kwargs.items()
    }
    return args, kwargs


def _store_results(rt: WorkerRuntime, spec: TaskSpec, out) -> list:
    if spec.num_returns == 1:
        out = [out]
    elif spec.num_returns == 0:
        out = []
    else:
        out = list(out)
        if len(out) != spec.num_returns:
            raise ValueError(
                f"task {spec.name} declared num_returns={spec.num_returns} "
                f"but returned {len(out)} values"
            )
    # Serialize EVERY result before sending any bookkeeping: a failure on
    # result k after result 0's guards went out would leak those borrows
    # (the task then reports an error and no release path runs).
    serialized = []
    for i, value in enumerate(out):
        oid = f"o:{spec.task_id}:{i}"
        serialized.append((oid, ser.serialize(value)))
    results = []
    guarded: list = []
    try:
        for oid, (payload, buffers, contained) in serialized:
            rt.note_escaped(contained)  # refs we own, leaving via our result
            # Guard borrows, sent WHILE the contained refs are still alive
            # in this frame: the executor's own ObjectRefs die at frame
            # teardown (their refop dels hit the conn before the done/seal
            # messages), so without a preceding add the owner could free a
            # contained child in the del→done window.  The owner releases
            # the guard once its own stored-object borrow is in place
            # (_on_task_done / direct_seal); for caller-owned inline direct
            # results the guard IS the caller-cache borrow, released when
            # the cache entry drops.
            for c in contained:
                rt.oneway(("refop", "add", c))
                guarded.append(c)
            size = len(payload) + sum(len(b.raw()) for b in buffers)
            if size >= inline_threshold():
                packed = rt.shm.create(oid, payload, buffers)
                from ray_tpu._private import telemetry as _telemetry

                _telemetry.count_copy("seal", packed)
                results.append((oid, "shm", packed, contained))
            else:
                results.append(
                    (oid, "inline", bytes(ser.pack(payload, buffers)), contained)
                )
    except BaseException:
        for c in guarded:  # storage failed: balance the sent guards
            rt.oneway(("refop", "del", c))
        raise
    return results


def _execute(rt: WorkerRuntime, spec: TaskSpec, blob: Optional[bytes]):
    """Run one task/actor-method/creation; returns ("done", ...) message."""
    import contextlib

    from ray_tpu.util import tracing

    _ctx_token = _current_task.set(spec.task_id)
    stack = contextlib.ExitStack()
    if getattr(spec, "trace_ctx", None) is not None and tracing.is_enabled():
        # Adopt the submitter's context: this run span parents to its
        # submit span, and anything WE submit parents to this run
        # (ray: tracing_helper.py execute-side wrapper).
        stack.enter_context(
            tracing.span(
                f"run::{spec.name}",
                parent=spec.trace_ctx,
                attrs={"task_id": spec.task_id, "worker_id": rt.worker_id},
            )
        )
    try:
        if spec.is_actor_creation:
            cls = rt.resolve_function(spec.fn_id, blob)
            from ray_tpu._private import faults

            if faults.ENABLED:
                # Scope chaos clauses by the hosted actor class
                # (proc=actor:<Class>) — set BEFORE __init__ so creation
                # is inside the scope too.
                faults.set_process_tag(
                    f"worker:{rt.worker_id}:actor:{cls.__name__}"
                )
            args, kwargs = _resolve_args(rt, spec.args_blob)
            rt.current_actor = cls(*args, **kwargs)
            rt.current_actor_id = spec.actor_id
            rt.current_actor_spec = spec
            results = _store_results(rt, spec, None)
        elif spec.actor_id is not None:
            method = getattr(rt.current_actor, spec.method_name)
            args, kwargs = _resolve_args(rt, spec.args_blob)
            out = method(*args, **kwargs)
            if _is_coroutine(out):
                out = _run_on_actor_loop(rt, out)
            results = _store_results(rt, spec, out)
        else:
            fn = rt.resolve_function(spec.fn_id, blob)
            args, kwargs = _resolve_args(rt, spec.args_blob)
            out = fn(*args, **kwargs)
            if _is_coroutine(out):
                import asyncio

                out = asyncio.run(out)
            results = _store_results(rt, spec, out)
        return ("done", spec.task_id, results, None)
    except BaseException as e:  # noqa: BLE001 -- remote errors must be reported
        if isinstance(e, SystemExit):
            raise
        err = TaskError.from_exception(spec.name, e)
        import cloudpickle

        return ("done", spec.task_id, [], cloudpickle.dumps(err))
    finally:
        stack.close()  # end the run span (records it for the next flush)
        _current_task.reset(_ctx_token)


def _is_coroutine(x) -> bool:
    import inspect

    return inspect.iscoroutine(x)


def _run_on_actor_loop(rt: WorkerRuntime, coro):
    """Run a coroutine on the actor's persistent event loop (async actors).

    The task-id ContextVar is re-set INSIDE the wrapping coroutine: the
    loop thread has its own context, and each asyncio Task gets an isolated
    copy, so concurrent async methods keep distinct parents."""
    import asyncio

    if rt.async_loop is None:
        # Locked double-check: concurrent FIRST async calls (threaded
        # max_concurrency pool) racing this create would split the actor's
        # coroutines across two loops — asyncio primitives (Event, Lock)
        # created on one loop then awaited on the other raise
        # "bound to a different event loop".
        with rt._async_loop_lock:
            if rt.async_loop is None:
                loop = asyncio.new_event_loop()
                t = threading.Thread(
                    target=loop.run_forever, daemon=True, name="actor-asyncio"
                )
                t.start()
                rt.async_loop = loop
    task_id = current_task_id()

    async def _with_context():
        token = _current_task.set(task_id)
        try:
            return await coro
        finally:
            _current_task.reset(token)

    fut = asyncio.run_coroutine_threadsafe(_with_context(), rt.async_loop)
    return fut.result()


def worker_main(address, authkey: bytes, worker_id: str, session_name: str, env_vars):
    # Apply runtime-env vars FIRST, before any heavy import (so e.g.
    # JAX_PLATFORMS / XLA_FLAGS take effect in this process).
    if env_vars:
        os.environ.update(env_vars)
    if os.environ.get("RAY_TPU_BOOT_TRACE"):
        import time as _t

        _boot_t0 = _t.monotonic()

        def _tr(label):
            print(f"BOOT {label} +{1000*(_t.monotonic()-_boot_t0):.1f}ms", flush=True)
    else:
        def _tr(label):
            pass
    _tr("start")
    if os.environ.get("RAY_TPU_FAULTHANDLER"):
        import faulthandler
        import signal as _sig

        faulthandler.register(_sig.SIGUSR1, all_threads=True)
    if os.environ.get("RAY_TPU_PDEATHSIG"):
        # Daemon-owned worker: die when the node daemon dies, even on
        # SIGKILL of the daemon (node-failure semantics — a raylet's
        # workers don't outlive it).  Linux prctl(PR_SET_PDEATHSIG); where
        # unavailable, a watchdog thread polls for reparenting instead so
        # the invariant holds on every platform.
        armed = False
        try:
            import ctypes
            import signal as _signal

            ctypes.CDLL(None).prctl(1, _signal.SIGTERM)  # PR_SET_PDEATHSIG=1
            armed = True
        except Exception:
            pass
        if not armed:
            import time as _time

            parent = os.getppid()

            def _orphan_watch():
                while True:
                    _time.sleep(2.0)
                    if os.getppid() != parent:
                        os._exit(0)

            threading.Thread(target=_orphan_watch, daemon=True).start()
    global _runtime
    from ray_tpu._private import telemetry, wire

    # Flight recorder armed before anything can crash: a fault-plane kill
    # or uncaught exception in this worker dumps its recent-event ring.
    telemetry.install(f"worker:{worker_id}")

    # Watchdog: if the connect/auth handshake wedges (e.g. the driver
    # vanished between spawn and connect), die instead of lingering — the
    # driver's reaper then reschedules anything leased to this worker.
    from ray_tpu._private import config as _cfg

    watchdog = threading.Timer(
        _cfg.get("worker_handshake_timeout_s"), lambda: os._exit(17)
    )
    watchdog.daemon = True
    watchdog.start()
    conn = wire.batching(wire.connect(address, authkey))
    watchdog.cancel()
    _tr("connected")
    from ray_tpu._private.netutil import set_nodelay

    set_nodelay(conn)
    conn_lock = lock_watchdog.make_lock("worker_main.conn_lock")
    rt = WorkerRuntime(conn, conn_lock, session_name, worker_id, authkey=authkey)
    _runtime = rt
    _tr("runtime")

    # Install ObjectRef refcount hooks: proxy to owner (oneway, FIFO with the
    # task's own completion message so no use-after-free races).
    from ray_tpu._private import refs as refs_mod

    # Locally-owned direct-call results are counted in-process; everything
    # else proxies to the owner as before.
    refs_mod.set_ref_hooks(rt.borrow_ref, rt.unborrow_ref)
    # Mark this process as a worker for ray_tpu API routing.
    from ray_tpu._private import runtime as runtime_mod

    runtime_mod._worker_mode = True



    task_q: "queue.Queue[tuple]" = queue.Queue()
    pool = None  # ThreadPoolExecutor for max_concurrency > 1
    pool_lock = threading.Lock()

    node_id = os.environ.get("RAY_TPU_NODE_ID")

    # -- direct peer transport (ray: direct_actor_task_submitter.h:67) -----
    # The peer server's endpoint rides the "ready" handshake; peer-pushed
    # tasks execute on the SAME queues as head-pushed ones (per-caller
    # order = the pushing connection's FIFO), replying on the peer socket.
    from ray_tpu._private.peer import DirectTransport, PeerServer

    def route_task(msg: tuple, reply) -> None:
        """Route one executable task to the right executor (shared by the
        head recv loop and every peer connection)."""
        nonlocal pool
        spec: TaskSpec = msg[1]
        # Lifecycle stamp: when this executor dequeued the frame — the
        # "received" stage of the task state machine (one attribute set;
        # TaskSpec is a plain dataclass, the rider never hits the wire
        # twice because the spec is executed, not forwarded).
        spec._recv_t = time.time()
        if spec.max_concurrency > 1 and not spec.is_actor_creation:
            from concurrent.futures import ThreadPoolExecutor

            with pool_lock:
                if pool is None:
                    pool = ThreadPoolExecutor(max_workers=spec.max_concurrency)
            pool.submit(_run_and_reply, msg, reply)
        else:
            task_q.put((msg, reply))

    peer_cancelled: set = set()

    # Task events for peer-executed tasks, reported to the head in BATCHES
    # off the latency path (ray: task_event_buffer.h:147 — the reference
    # buffers and flushes task state transitions on an interval too; the
    # state API is eventually consistent in both systems).
    events_buf: list = []
    events_lock = threading.Lock()

    def flush_task_events() -> None:
        from ray_tpu._private import telemetry as _telemetry
        from ray_tpu.util import tracing as _tracing

        spans = _tracing.drain_spans()
        if spans:
            rt.oneway(("spans", spans), droppable=True)
        with events_lock:
            if not events_buf:
                return
            batch = events_buf[:]
            events_buf.clear()
        _telemetry.note("task_events_flush", n=len(batch))
        rt.oneway(("task_events", batch), droppable=True)

    def record_peer_task_event(spec, err_blob, t0: float, t1: float) -> None:
        recv_t = getattr(spec, "_recv_t", None) or t0
        with events_lock:
            events_buf.append(
                {
                    "task_id": spec.task_id,
                    "name": spec.name,
                    "state": "FINISHED" if err_blob is None else "FAILED",
                    "node_id": node_id,
                    "worker_id": worker_id,
                    "actor_id": spec.actor_id,
                    "parent_task_id": spec.parent_task_id,
                    "attempt": spec.attempt,
                    "end_time": t1,
                    "duration": t1 - t0,
                    "direct": True,
                    # Executor-side stage attribution for direct tasks
                    # (the head sees no dispatch for these, so the
                    # exec-queue + run split is all it can know).
                    "stages": {
                        "received": recv_t, "running": t0, "exec_done": t1,
                    },
                    "durations": {
                        "exec_queue": round(max(t0 - recv_t, 0.0), 6),
                        "running": round(max(t1 - t0, 0.0), 6),
                    },
                }
            )
            full = len(events_buf) >= 64
        if full:
            flush_task_events()

    def _sink_event(e: dict) -> None:
        with events_lock:
            events_buf.append(e)
            full = len(events_buf) >= 64
        if full:
            flush_task_events()

    rt.task_event_sink = _sink_event
    ready_sent = threading.Event()

    def _on_prof_ctl(_key, action, *args) -> None:
        """Cluster profiler broadcast handler ("profiler"/"ctl" pubsub):
        start/stop the local sampler; a stop pushes the final table
        immediately so the head's report window closes tight."""
        from ray_tpu._private import profiler as _profiler

        if action == "start":
            _profiler.start(args[0] if args else None)
        elif action == "stop":
            _profiler.stop()
            rt.oneway(
                ("prof_push", _profiler.snapshot_payload()), droppable=True
            )

    def _events_ticker() -> None:
        import time as _time

        from ray_tpu._private import config as _cfg2
        from ray_tpu._private import profiler as _profiler
        from ray_tpu._private import telemetry as _telemetry

        report_wire = bool(_cfg2.get("wire_stats"))
        push_s = max(_cfg2.get("metrics_push_ms"), 0) / 1000.0
        push_refs = bool(_cfg2.get("refs_push"))
        last_push = 0.0
        prof_subscribed = False
        while True:
            _time.sleep(0.5)
            if not ready_sent.is_set():
                # NOTHING may precede the ready hello on this conn: the
                # head's handshake dispatcher closes a conn whose first
                # message is not a recognized hello — a push racing a
                # slow runtime-env setup would sever the very conn the
                # env_failed report needs.
                continue
            if not prof_subscribed:
                # One subscription per worker, armed only after the ready
                # hello: profiler start/stop broadcasts now reach this
                # process for its whole life.
                prof_subscribed = True
                try:
                    rt.subscribe("profiler", "ctl", _on_prof_ctl)
                except OSError:
                    prof_subscribed = False  # head away: retry next beat
                else:
                    try:
                        # Catch up: a cluster-wide profile started before
                        # this worker existed never reached it (pubsub is
                        # live-only) — poll the head's sampler state once.
                        st = rt.request("profile", ("status",), timeout=5)
                        if st and st.get("running"):
                            _on_prof_ctl(None, "start", st.get("hz"))
                    except Exception:
                        pass  # the next start broadcast still reaches us
            flush_task_events()
            if report_wire:
                rt.oneway(("wire_stats", wire.stats()), droppable=True)
            if push_s > 0 and _time.monotonic() - last_push >= push_s:
                # Metric push (telemetry.py): this process's util/metrics
                # registry + wire counters, droppable by contract — a head
                # bounce loses a tick, never wedges the backlog.
                last_push = _time.monotonic()
                rt.oneway(
                    ("metrics_push", _telemetry.snapshot_process()),
                    droppable=True,
                )
                if push_refs:
                    # Live-ref table push (the worker leg of the object
                    # ledger): same tick, same droppable contract — it
                    # never competes with seals/refops for the backlog.
                    rt.oneway(
                        ("refs_push", rt.ref_table_snapshot()),
                        droppable=True,
                    )
                if _profiler.ENABLED and _profiler.running():
                    # Collapsed-stack push (the worker leg of the cluster
                    # flamegraph): cumulative table, so a dropped push
                    # costs freshness only.  Gated on the module bool —
                    # profiler off costs exactly this one check.
                    rt.oneway(
                        ("prof_push", _profiler.snapshot_payload()),
                        droppable=True,
                    )
            # Telemetry rides the next linger/idle flush; nudge it here so
            # a fully-busy executor still reports within a beat.
            wire.flush_dirty()

    threading.Thread(
        target=_events_ticker, daemon=True, name="raytpu-task-events"
    ).start()

    def peer_handler(msg: tuple, reply) -> None:
        if msg[0] == "pcall":
            spec = msg[1]
            if (
                spec.actor_id is None
                and not spec.is_actor_creation
                and spec.max_concurrency <= 1
            ):
                # Leased plain task: execute INLINE on this conn's recv
                # thread.  A leased worker serves exactly ONE caller and
                # the conn is its FIFO, so ordering and serialization
                # are identical to the task_q route — what disappears is
                # the queue handoff (two futex waits + a context switch
                # per task, a measured slice of per-task wall on a
                # contended host).  Actor calls keep the queue: their
                # cross-conn ordering and max_concurrency semantics live
                # there.
                spec._recv_t = time.time()
                _run_and_reply(("task", spec, None), reply)
                return
            route_task(("task", msg[1], None), reply)
        elif msg[0] == "pcancel":
            # Best-effort: queued (not yet started) calls are dropped at
            # execution time; a running method is never interrupted.
            # Bounded — a cancel for a running/finished task would
            # otherwise park in the set forever (evicting an arbitrary
            # stale entry only downgrades that cancel to a no-op).
            if len(peer_cancelled) >= 4096:
                peer_cancelled.pop()
            peer_cancelled.add(msg[1])

    advertise = os.environ.get("RAY_TPU_PEER_HOST") or (
        address[0] if isinstance(address, tuple) else "127.0.0.1"
    )
    if advertise in ("0.0.0.0", "::", ""):
        # The head listener may bind a wildcard (RAY_TPU_BIND_HOST=0.0.0.0)
        # — unroutable as an advertised address (a remote peer would dial
        # its OWN loopback); fall back to this node's routable IP knob.
        advertise = _cfg.get("node_ip")
    bind = "127.0.0.1" if advertise in ("127.0.0.1", "localhost") else "0.0.0.0"
    try:
        peer_server = PeerServer(authkey, bind, advertise, peer_handler)
        peer_endpoint = peer_server.endpoint
    except OSError:
        peer_server, peer_endpoint = None, None  # no direct path; head relays
    rt.direct = DirectTransport(rt)
    _tr("peer_server")

    def try_reconnect() -> bool:
        """Head conn lost: in head-split mode (reconnect window > 0) retry
        the head's FIXED address and re-handshake; a restarted head adopts
        this worker (ray: workers surviving a GCS restart re-register)."""
        from ray_tpu._private import config as _cfg

        window = _cfg.get("reconnect_window_s")
        if window <= 0:
            return False
        import time as _time

        deadline = _time.monotonic() + window
        newconn = None
        while _time.monotonic() < deadline:
            try:
                newconn = wire.batching(wire.connect(address, authkey))
                set_nodelay(newconn)
                break
            except Exception:
                _time.sleep(0.5)
        if newconn is None:
            return False
        # Swap + hello + backlog flush + request-fail + replays run in ONE
        # shared implementation (WorkerRuntime.reconnect_recover — the
        # attached-driver path uses the same one).
        import time as _time

        return rt.reconnect_recover(
            newconn,
            # The trailing list is the relayed-work announcement: tasks
            # this executor still holds (queued or running).  The head
            # re-drives exactly the in-flight work NOT in this list — it
            # was lost with the dead conn (reconciliation handshake,
            # executor leg; the shard fabric's conn-death recovery).
            lambda c: c.send(
                ("ready", worker_id, os.getpid(), node_id, peer_endpoint,
                 rt.actor_announcement(), _time.time(),
                 list(rt.relayed_pending))
            ),
        )

    def recv_loop():
        while True:
            try:
                msg = rt.conn.recv()
            except (EOFError, OSError):
                if not try_reconnect():
                    os._exit(0)
                continue
            kind = msg[0]
            if kind == "reply":
                rt._on_reply(msg[1], msg[2], msg[3])
            elif kind == "pub":
                rt._on_pub(msg[1], msg[2], msg[3])
            elif kind in ("task", "create_actor"):
                # Track BEFORE enqueueing: a reconnect hello built between
                # receipt and execution must still announce this task.
                try:
                    rt.relayed_pending[msg[1].task_id] = None
                except AttributeError:
                    pass
                route_task(msg, None)
            elif kind == "fence":
                # Transport-switch barrier: acking from the recv thread
                # certifies every earlier task on this conn is already in
                # the executor queue — a direct call sent after the ack
                # cannot overtake a relayed one (see peer.py docstring).
                # The head is parked on this ack: flush immediately.
                rt.oneway(("fence_ack", msg[1]))
                try:
                    wire.flush_conn(rt.conn)
                except OSError:
                    pass
            elif kind == "kill":
                os._exit(0)
            elif kind == "shutdown":
                task_q.put((("__shutdown__",), None))

    def _run_and_reply(msg, reply=None):
        spec, blob = msg[1], msg[2]
        if reply is not None and spec.task_id in peer_cancelled:
            peer_cancelled.discard(spec.task_id)
            import cloudpickle

            from ray_tpu.exceptions import TaskCancelledError

            reply.send(
                ("pdone", spec.task_id, [],
                 cloudpickle.dumps(TaskCancelledError(spec.name)))
            )
            return
        import time as _time

        t0 = _time.time()
        try:
            done = _execute(rt, spec, blob)
        except SystemExit:
            # exit_actor() from a concurrent (thread-pool) actor method:
            # in a pool thread SystemExit would be swallowed by the Future,
            # leaving the caller hanging — exit the process here (the
            # actor_exit oneway was already sent by exit_actor()).
            os._exit(0)
        if reply is None:
            # Executor-side stage stamps ride the done message (schema
            # arity 4): recv = frame dequeued, start/end = user code.
            # The head lands them on its clock via the handshake offset
            # and folds them into the task's lifecycle record.
            done = done + (
                {
                    "recv": getattr(spec, "_recv_t", None) or t0,
                    "start": t0,
                    "end": _time.time(),
                },
            )
            try:
                with conn_lock:
                    rt.conn.send(done)
            except OSError:
                pass  # head restarting: this result is lost; recv_loop reconnects
            # Replied (or the send failed — then the result is lost either
            # way): no longer pending, so a reconnect hello will NOT claim
            # it and the head re-drives it if the done never landed.
            rt.relayed_pending.pop(spec.task_id, None)
            return
        # Direct-call completion: registration oneways go to the head first
        # (FIFO behind the guard borrows _store_results already sent), then
        # the caller unblocks via the peer socket.  Inline results send
        # nothing — they are caller-owned, and the serialize-time guard
        # doubles as the caller-cache borrow.
        _task_id, results, err_blob = done[1], done[2], done[3]
        if (
            err_blob is None
            and spec.actor_id is None
            and any(item[1] == "shm" for item in results)
        ):
            # Sealed PLAIN-task results are reconstructable: ship the spec
            # so the head keeps lineage for this lease-dispatched task
            # (ray: task_manager.h:90 — owner-side lineage regardless of
            # transport; actor-method outputs are excluded exactly like the
            # relayed path — re-running a stateful method is not recovery).
            # Must precede the direct_seal below (same FIFO) so lineage
            # exists before the object is ever resolvable.
            rt.oneway(("direct_lineage", spec))
        for item in results:
            oid, kind, data, contained = item
            if kind == "shm":
                # Register the sealed copy with the directory so remote
                # consumers (and capacity accounting) can find it; the head
                # swaps the guard borrows for its stored-object borrows.
                rt.oneway(("direct_seal", oid, data, contained))
        record_peer_task_event(spec, err_blob, t0, _time.time())
        reply.send(("pdone", _task_id, results, err_blob))

    threading.Thread(target=recv_loop, daemon=True, name="worker-recv").start()

    # Materialize working_dir / py_modules BEFORE the ready handshake (no
    # task may run before its code exists).  Packages come over dedicated
    # one-shot kv_fetch connections: the main conn cannot serve requests
    # yet — the owner parks replies behind "ready".
    renv_json = os.environ.get("RAY_TPU_RUNTIME_ENV")
    if renv_json:
        import json as _json

        from ray_tpu._private.runtime_env import apply_worker_runtime_env

        def _fetch(key):
            c = wire.connect(address, authkey)
            try:
                c.send(("kv_fetch", key))
                return c.recv()
            finally:
                c.close()

        try:
            apply_worker_runtime_env(_json.loads(renv_json), kv_get=_fetch)
        except Exception as e:  # noqa: BLE001 — report, then die
            # Setup failure is deterministic: report it as a structured
            # env_failed hello so the head fails the leased task with
            # RuntimeEnvSetupError instead of a retriable worker crash.
            try:
                with conn_lock:
                    conn.send(("env_failed", worker_id, f"{type(e).__name__}: {e}"))
                wire.flush_conn(conn)
            except OSError:
                pass
            sys.exit(1)

    _tr("pre_ready")
    with conn_lock:
        # The trailing time.time() is the clock-offset sample the head
        # uses to merge this process's spans into the cluster timeline.
        conn.send(
            ("ready", worker_id, os.getpid(), node_id, peer_endpoint,
             None, time.time())
        )
    wire.flush_conn(conn)
    ready_sent.set()  # telemetry oneways may ride this conn from here on

    while True:
        try:
            msg, reply = task_q.get_nowait()
        except queue.Empty:
            # About to block on the task queue: flush every pending batch
            # (done/refop runs to the head, pdone runs to peer callers).
            # While tasks are queued back-to-back, consecutive results
            # keep coalescing — the linger sweep bounds their latency.
            wire.flush_dirty()
            msg, reply = task_q.get()
        if msg[0] == "__shutdown__":
            break
        _run_and_reply(msg, reply)
    wire.flush_dirty()
    sys.exit(0)


def _subprocess_entry() -> None:
    """Entry for `python -m ray_tpu._private.worker_proc` (exec'ed by the
    driver's worker pool — see runtime._spawn_worker)."""
    import json

    host = os.environ["RAY_TPU_DRIVER_HOST"]
    port = int(os.environ["RAY_TPU_DRIVER_PORT"])
    authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
    wid = os.environ["RAY_TPU_WORKER_ID"]
    session = os.environ["RAY_TPU_SESSION"]
    env_vars = json.loads(os.environ.get("RAY_TPU_ENV_VARS", "{}"))
    # Under `python -m` this file runs as __main__; call through the
    # canonical module so worker_main's globals (the _runtime singleton)
    # land where `import ray_tpu._private.worker_proc` reads them.
    from ray_tpu._private import worker_proc as canonical

    canonical.worker_main((host, port), authkey, wid, session, env_vars)


if __name__ == "__main__":
    _subprocess_entry()
