"""Core-runtime microbenchmarks (ray: python/ray/_private/ray_perf.py:93).

Same workload shapes as the reference's `ray microbenchmark` so the numbers
in BENCH_core_r*.json are comparable with BASELINE.md's table:

  single_client_tasks_sync      submit f.remote(); get() one at a time
  single_client_tasks_async     submit a window of tasks, get in batches
  multi_client_tasks_async      N driver threads submitting concurrently
  1_1_actor_calls_sync          one handle, call+get sequentially
  1_1_actor_calls_async         one handle, windowed submission
  n_n_actor_calls_async         N handles, N submitting threads
  single_client_put_ops         small ray_tpu.put() throughput
  single_client_put_gigabytes   1GB of 100MB puts + gets (zero-copy path)

Run: `python -m ray_tpu._private.ray_perf [--json out.json]`
"""

from __future__ import annotations

import json
import sys
import time
from typing import Callable, Dict, List

import ray_tpu


def timeit(name: str, fn: Callable[[], int], warmup: int = 1, repeat: int = 3):
    """Run fn (returns ops count) repeat times; report the MEDIAN ops/s as
    the headline (all runs listed).  Best-of-N on a shared host with ±35%
    variance reports the luckiest scheduling window, which both masks and
    fakes real regressions/wins — the median is the honest number.

    Also reports this process's physical control-plane writes per op
    (wire.stats delta over the timed runs): the deterministic coalescing
    metric that doesn't care about host noise.  With the GCS mutation
    journal active (RAY_TPU_PERF_PERSIST=1), journal appends and fsyncs
    per op ride along the same way — the durability-cost twin of
    writes_per_op."""
    import statistics

    from ray_tpu._private import wire as _wire

    def _journal_counts():
        try:
            from ray_tpu._private.runtime import get_runtime

            rt = get_runtime()
            j = getattr(rt, "_journal", None)
            if j is None:
                return None
            # Flush so the physical-write count reflects the timed work
            # (a pending group-commit batch would undercount).
            j.flush()
            return (j.writes, j.fsyncs, j.entries)
        except Exception:
            return None

    for _ in range(warmup):
        fn()
    runs: List[float] = []
    w0 = _wire.stats()
    j0 = _journal_counts()
    total_ops = 0
    for _ in range(repeat):
        t0 = time.perf_counter()
        ops = fn()
        dt = time.perf_counter() - t0
        runs.append(round(ops / dt, 1))
        total_ops += ops
    w1 = _wire.stats()
    j1 = _journal_counts()
    out = {
        "name": name,
        "ops_per_s": round(statistics.median(runs), 1),
        "runs": runs,
    }
    if total_ops:
        out["writes_per_op"] = round(
            (w1["physical_writes"] - w0["physical_writes"]) / total_ops, 3
        )
        out["frames_per_op"] = round(
            (w1["logical_frames"] - w0["logical_frames"]) / total_ops, 3
        )
        # Codec split (this process): pickle bodies per op is the
        # native-codec acceptance counter — deterministic, unlike ops/s.
        out["pickle_codecs_per_op"] = round(
            (
                w1["pickle_encodes"] + w1["pickle_decodes"]
                - w0["pickle_encodes"] - w0["pickle_decodes"]
            ) / total_ops, 3
        )
        if j0 is not None and j1 is not None:
            # journal_appends = PHYSICAL writes (group-committed);
            # journal_entries = logical mutations.  Their ratio is the
            # group-commit factor.
            out["journal_appends_per_op"] = round((j1[0] - j0[0]) / total_ops, 3)
            out["journal_fsyncs_per_op"] = round((j1[1] - j0[1]) / total_ops, 3)
            out["journal_entries_per_op"] = round((j1[2] - j0[2]) / total_ops, 3)
    return out


def host_shape() -> Dict:
    """Self-describing host header for every BENCH json: cpu count, load
    average at the run, and the cgroup cpu quota when one applies — a
    1-vCPU artifact must SAY it is one (BENCH_shard_r1's honesty note,
    promoted into the data)."""
    import os as _os

    shape: Dict = {"nproc": _os.cpu_count()}
    try:
        shape["loadavg_1m"], shape["loadavg_5m"], shape["loadavg_15m"] = (
            round(x, 2) for x in _os.getloadavg()
        )
    except OSError:
        pass
    # cgroup v2 then v1: quota/period -> effective cores; "max" = no cap.
    try:
        with open("/sys/fs/cgroup/cpu.max") as f:
            quota, period = f.read().split()
            if quota != "max":
                shape["cgroup_cpus"] = round(int(quota) / int(period), 2)
            else:
                shape["cgroup_cpus"] = None
    except OSError:
        try:
            with open("/sys/fs/cgroup/cpu/cpu.cfs_quota_us") as f:
                quota = int(f.read())
            with open("/sys/fs/cgroup/cpu/cpu.cfs_period_us") as f:
                period = int(f.read())
            shape["cgroup_cpus"] = (
                round(quota / period, 2) if quota > 0 else None
            )
        except OSError:
            pass
    return shape


def _enable_local_persistence() -> None:
    """RAY_TPU_PERF_PERSIST=1: run the benches with the snapshot loop AND
    the mutation journal active on the local runtime, exactly as a
    standalone head runs them — so journal_appends_per_op /
    journal_fsyncs_per_op measure the real durability tax on the hot
    path (the honesty requirement: BENCH_core medians must stay within
    noise of the journal-less tree)."""
    import os as _os
    import threading as _threading

    from ray_tpu._private import config as _config
    from ray_tpu._private.gcs_storage import (
        make_mutation_journal,
        make_snapshot_storage,
    )
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    if rt._snapshot_storage is not None:
        return  # already persistent (attached to a real head)
    d = f"/tmp/raytpu-perf-{_os.getpid()}"
    _os.makedirs(d, exist_ok=True)
    path = _os.path.join(d, "gcs_snapshot.pkl")
    rt.snapshot_path = path
    rt._snapshot_storage = make_snapshot_storage(path)
    rt._journal = make_mutation_journal(path, rt.session_name)
    rt._journal_compact_bytes = _config.get("gcs_journal_compact_bytes")
    rt.state.journal_hook = rt._journal_append
    _threading.Thread(
        target=rt._snapshot_loop, daemon=True, name="raytpu-snapshot"
    ).start()


@ray_tpu.remote
def _noop(*args):
    return None


@ray_tpu.remote
class _Actor:
    def noop(self, *args):
        return None


def bench_tasks_sync(n: int = 300) -> Dict:
    def run():
        for _ in range(n):
            ray_tpu.get(_noop.remote(), timeout=60)
        return n

    return timeit("single_client_tasks_sync", run)


def bench_tasks_async(n: int = 2000, window: int = 100) -> Dict:
    def run():
        refs: List = []
        for _ in range(n):
            refs.append(_noop.remote())
            if len(refs) >= window:
                ray_tpu.get(refs, timeout=120)
                refs = []
        if refs:
            ray_tpu.get(refs, timeout=120)
        return n

    return timeit("single_client_tasks_async", run)


def bench_multi_client_tasks_async(n_clients: int = 4, n_per: int = 1000) -> Dict:
    """N worker-process clients each fanning out plain tasks (the
    reference's multi-client shape — its clients are worker-side too, so
    each rides its own transport: here, head-granted leases + direct push
    instead of a per-task head request)."""
    clients = [_Client.remote() for _ in range(n_clients)]
    ray_tpu.get([c.run_tasks.remote(1, 1) for c in clients], timeout=60)

    def run():
        done = ray_tpu.get(
            [c.run_tasks.remote(n_per, 100) for c in clients], timeout=300
        )
        return sum(done)

    out = timeit("multi_client_tasks_async", run)
    for c in clients:
        ray_tpu.kill(c)
    return out


def bench_actor_calls_sync(n: int = 500) -> Dict:
    a = _Actor.remote()
    ray_tpu.get(a.noop.remote(), timeout=60)

    def run():
        for _ in range(n):
            ray_tpu.get(a.noop.remote(), timeout=60)
        return n

    out = timeit("1_1_actor_calls_sync", run)
    ray_tpu.kill(a)
    return out


def bench_actor_calls_async(n: int = 3000, window: int = 200) -> Dict:
    a = _Actor.remote()
    ray_tpu.get(a.noop.remote(), timeout=60)

    def run():
        refs = []
        for _ in range(n):
            refs.append(a.noop.remote())
            if len(refs) >= window:
                ray_tpu.get(refs, timeout=120)
                refs = []
        if refs:
            ray_tpu.get(refs, timeout=120)
        return n

    out = timeit("1_1_actor_calls_async", run)
    ray_tpu.kill(a)
    return out


@ray_tpu.remote(num_cpus=0.05)
class _Client:
    """Driving client hosted in a worker process — the reference's
    multi-client microbenchmarks also fan out from worker-side clients, so
    each client's calls ride its own core-worker transport (here: the
    direct peer path, zero head messages per call).

    Near-zero CPU demand: a client spends its life blocked in get(), and
    full-CPU clients on a small host would hold the very cores their leaf
    tasks need (nested-resource deadlock)."""

    def run_actor_calls(self, handle, n, window):
        refs = []
        for _ in range(n):
            refs.append(handle.noop.remote())
            if len(refs) >= window:
                ray_tpu.get(refs, timeout=120)
                refs = []
        if refs:
            ray_tpu.get(refs, timeout=120)
        return n

    def run_tasks(self, n, window):
        refs = []
        for _ in range(n):
            refs.append(_noop.remote())
            if len(refs) >= window:
                ray_tpu.get(refs, timeout=120)
                refs = []
        if refs:
            ray_tpu.get(refs, timeout=120)
        return n


def bench_n_n_actor_calls_async(n_actors: int = 4, n_per: int = 1000) -> Dict:
    actors = [_Actor.remote() for _ in range(n_actors)]
    clients = [_Client.remote() for _ in range(n_actors)]
    ray_tpu.get([a.noop.remote() for a in actors], timeout=60)
    ray_tpu.get(
        [c.run_actor_calls.remote(a, 1, 1) for c, a in zip(clients, actors)],
        timeout=60,
    )

    def run():
        done = ray_tpu.get(
            [
                c.run_actor_calls.remote(a, n_per, 100)
                for c, a in zip(clients, actors)
            ],
            timeout=300,
        )
        return sum(done)

    out = timeit("n_n_actor_calls_async", run)
    for a in actors + clients:
        ray_tpu.kill(a)
    return out


def bench_put_ops(n: int = 2000) -> Dict:
    def run():
        for i in range(n):
            ray_tpu.put(i)
        return n

    return timeit("single_client_put_ops", run)


def _copy_stats_delta(before: Dict, after: Dict) -> Dict:
    """{path: {copies, bytes, bytes_per_copy}} from two copy-counter
    snapshots (telemetry.copy_counter_snapshot) — the object plane's
    deterministic cost metric, same role writes_per_op plays for the
    control plane."""
    out: Dict = {}
    for path, rec in after.items():
        b = before.get(path, {"copies": 0.0, "bytes": 0.0})
        copies = rec.get("copies", 0.0) - b.get("copies", 0.0)
        nbytes = rec.get("bytes", 0.0) - b.get("bytes", 0.0)
        if copies > 0:
            out[path] = {
                "copies": int(copies),
                "bytes": int(nbytes),
                "bytes_per_copy": round(nbytes / copies, 1),
            }
    return out


def _cluster_copy_stats() -> Dict:
    """Cluster-wide copy counters: every process's pushed object_copies /
    object_copy_bytes series merged by the telemetry sink (workers count
    their own seals and pulls — the head's registry alone undercounts)."""
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    rt.telemetry.ingest("head", rt.head_telemetry_snapshot())
    agg = rt.telemetry.aggregate()
    out: Dict = {}
    for name, field in (("object_copies", "copies"), ("object_copy_bytes", "bytes")):
        rec = agg.get(name)
        for tk, v in (rec or {}).get("data", {}).items():
            path = dict(tk).get("path", "?")
            out.setdefault(path, {"copies": 0.0, "bytes": 0.0})[field] = float(v)
    return out


def bench_put_gigabytes(total_gb: float = 1.0, chunk_mb: int = 100) -> Dict:
    import numpy as np

    from ray_tpu._private import telemetry as _telemetry

    chunk = np.zeros(chunk_mb * 1024 * 1024, dtype=np.uint8)
    n_chunks = int(total_gb * 1024 / chunk_mb)

    def run():
        refs = [ray_tpu.put(chunk) for _ in range(n_chunks)]
        for r in refs:
            v = ray_tpu.get(r, timeout=120)
            assert v.nbytes == chunk.nbytes
        return 1

    # report GB/s moved (put+get of total_gb counts as total_gb); median
    # of the timed runs, same honesty rule as timeit
    import statistics

    for _ in range(1):
        run()
    runs = []
    c0 = _telemetry.copy_counter_snapshot()
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        runs.append(round(total_gb / dt, 2))
    return {
        "name": "single_client_put_gigabytes",
        "gb_per_s": round(statistics.median(runs), 2),
        "runs": runs,
        # bytes-per-copy on the put path (this process seals every chunk):
        # one sealed copy per put, packed size each — the one-copy
        # create/seal claim, counted rather than asserted.
        "copy_stats": _copy_stats_delta(
            c0, _telemetry.copy_counter_snapshot()
        ),
    }


ALL = [
    bench_tasks_sync,
    bench_tasks_async,
    bench_multi_client_tasks_async,
    bench_actor_calls_sync,
    bench_actor_calls_async,
    bench_n_n_actor_calls_async,
    bench_put_ops,
    bench_put_gigabytes,
]


# ---------------------------------------------------------------------------
# telemetry A/B: the observability plane's performance acceptance bar


def _multi_client_once(n_clients: int = 4, n_per: int = 1000) -> float:
    """One timed multi_client_tasks_async wave on the CURRENT cluster
    (fresh clients, one warm round): ops/s."""
    clients = [_Client.remote() for _ in range(n_clients)]
    ray_tpu.get([c.run_tasks.remote(1, 1) for c in clients], timeout=60)
    t0 = time.perf_counter()
    done = ray_tpu.get(
        [c.run_tasks.remote(n_per, 100) for c in clients], timeout=300
    )
    dt = time.perf_counter() - t0
    for c in clients:
        ray_tpu.kill(c)
    return round(sum(done) / dt, 1)


def refs_ab(out_path=None, rounds: int = 3, budget_pct: float = 3.0):
    """A/B the object-ledger leg ALONE: both sides run the full telemetry
    plane (push + trace + flight recorder); only RAY_TPU_REFS_PUSH (the
    live-ref table push + head-side ledger ingest) toggles.  This is the
    ISSUE 9 acceptance measurement — the ledger's own increment on
    multi_client_tasks_async must stay under budget.  (The whole-plane
    on/off number lives in telemetry_ab; on a noisy shared host the
    isolated toggle is the honest way to attribute cost to THIS leg.)

        python -m ray_tpu._private.ray_perf --refs-ab \
            [--json BENCH_refs_r1.json]
    """
    import os as _os
    import statistics

    from ray_tpu._private import config as _config
    from ray_tpu.util import tracing

    flight_dir = f"/tmp/raytpu-refsab-flight-{_os.getpid()}"
    saved = {
        k: _os.environ.get(k)
        for k in (
            "RAY_TPU_METRICS_PUSH_MS",
            "RAY_TPU_TRACE",
            "RAY_TPU_FLIGHT_DIR",
            "RAY_TPU_REFS_PUSH",
        )
    }
    runs = {"off": [], "on": []}
    try:
        # Full plane on BOTH sides.
        _os.environ["RAY_TPU_METRICS_PUSH_MS"] = "1000"
        _os.environ["RAY_TPU_TRACE"] = "1"
        _os.environ["RAY_TPU_FLIGHT_DIR"] = flight_dir
        tracing.enable_tracing()
        for _r in range(rounds):
            for mode in ("off", "on"):
                _os.environ["RAY_TPU_REFS_PUSH"] = "0" if mode == "off" else "1"
                _config._reset_for_tests()
                ray_tpu.init(num_cpus=max(_os.cpu_count() or 1, 16))
                try:
                    ops = _multi_client_once()
                finally:
                    ray_tpu.shutdown()
                runs[mode].append(ops)
                print(
                    json.dumps({"mode": mode, "round": _r, "ops_per_s": ops}),
                    flush=True,
                )
    finally:
        for k, v in saved.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
        _config._reset_for_tests()
        tracing.disable_tracing()
    off_m = statistics.median(runs["off"])
    on_m = statistics.median(runs["on"])
    overhead_pct = round((off_m - on_m) / off_m * 100, 2)
    report = {
        "name": "refs_push_ab_multi_client_tasks_async",
        "note": (
            "interleaved rounds, medians compared (median-of-"
            f"{rounds}).  BOTH sides run the full telemetry plane "
            "(RAY_TPU_METRICS_PUSH_MS=1000, RAY_TPU_TRACE=1, flight "
            "recorder armed); only RAY_TPU_REFS_PUSH toggles — the "
            "object-ledger leg (per-process live-ref tables pushed each "
            "tick + head-side ledger joins/gauges) is the only delta"
        ),
        "off_runs": runs["off"],
        "on_runs": runs["on"],
        "off_median_ops_per_s": off_m,
        "on_median_ops_per_s": on_m,
        "overhead_pct": overhead_pct,
        "budget_pct": budget_pct,
        "pass": overhead_pct < budget_pct,
    }
    print(json.dumps(report, indent=1), flush=True)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    assert overhead_pct < budget_pct, (
        f"refs-push leg costs {overhead_pct}% on multi_client_tasks_async "
        f"(budget {budget_pct}%): off={runs['off']} on={runs['on']}"
    )
    return report


def prof_ab(out_path=None, rounds: int = 3, budget_pct: float = 5.0):
    """A/B the sampling profiler ALONE on multi_client_tasks_async: both
    sides run the normal plane defaults; only RAY_TPU_PROF_HZ toggles
    between 0 (off — the zero-overhead fast path) and the default rate.
    Interleaved rounds, medians compared — the ISSUE 10 acceptance
    measurement (profiler on at default HZ must cost <5%).

        python -m ray_tpu._private.ray_perf --prof-ab \
            [--json BENCH_prof_r1.json]
    """
    import os as _os
    import statistics

    from ray_tpu._private import config as _config
    from ray_tpu._private import profiler as _profiler

    hz = _profiler.DEFAULT_HZ
    saved = _os.environ.get("RAY_TPU_PROF_HZ")
    runs = {"off": [], "on": []}
    try:
        for _r in range(rounds):
            for mode in ("off", "on"):
                _os.environ["RAY_TPU_PROF_HZ"] = (
                    "0" if mode == "off" else str(hz)
                )
                _config._reset_for_tests()
                _profiler._reset_for_tests()  # stop any prior sampler
                ray_tpu.init(num_cpus=max(_os.cpu_count() or 1, 16))
                try:
                    ops = _multi_client_once()
                finally:
                    ray_tpu.shutdown()
                    _profiler._reset_for_tests()
                runs[mode].append(ops)
                print(
                    json.dumps({"mode": mode, "round": _r, "ops_per_s": ops}),
                    flush=True,
                )
    finally:
        if saved is None:
            _os.environ.pop("RAY_TPU_PROF_HZ", None)
        else:
            _os.environ["RAY_TPU_PROF_HZ"] = saved
        _config._reset_for_tests()
        _profiler._reset_for_tests()
    off_m = statistics.median(runs["off"])
    on_m = statistics.median(runs["on"])
    overhead_pct = round((off_m - on_m) / off_m * 100, 2)
    report = {
        "name": "prof_ab_multi_client_tasks_async",
        "hz": hz,
        "note": (
            "interleaved OFF/ON rounds, medians compared (median-of-"
            f"{rounds}).  OFF = RAY_TPU_PROF_HZ unset (the ENABLED "
            "module-bool fast path: no thread, no per-op check beyond "
            "the ticker's one bool); ON = every process samples "
            f"sys._current_frames() at {hz}Hz and pushes collapsed-stack "
            "tables each telemetry tick"
        ),
        "off_runs": runs["off"],
        "on_runs": runs["on"],
        "off_median_ops_per_s": off_m,
        "on_median_ops_per_s": on_m,
        "overhead_pct": overhead_pct,
        "budget_pct": budget_pct,
        "pass": overhead_pct < budget_pct,
    }
    print(json.dumps(report, indent=1), flush=True)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    assert overhead_pct < budget_pct, (
        f"profiler at {hz}Hz costs {overhead_pct}% on "
        f"multi_client_tasks_async (budget {budget_pct}%): "
        f"off={runs['off']} on={runs['on']}"
    )
    return report


def telemetry_ab(out_path=None, rounds: int = 3, budget_pct: float = 3.0):
    """A/B the FULL telemetry plane (metric push + trace spans + flight
    recorder) against telemetry-off on the multi_client_tasks_async
    shape.  Runs interleave OFF/ON per round (drift on a shared host
    cancels instead of biasing one side) and the medians-of-N compare —
    the same honesty rule as the headline benches.  Asserts the overhead
    budget (<3% by the ISSUE 6 acceptance bar) and writes the artifact.

        python -m ray_tpu._private.ray_perf --telemetry-ab \
            [--json BENCH_telemetry_r1.json]
    """
    import os as _os
    import statistics

    from ray_tpu._private import config as _config
    from ray_tpu.util import tracing

    flight_dir = f"/tmp/raytpu-ab-flight-{_os.getpid()}"
    saved = {
        k: _os.environ.get(k)
        for k in (
            "RAY_TPU_METRICS_PUSH_MS",
            "RAY_TPU_TRACE",
            "RAY_TPU_FLIGHT_DIR",
            "RAY_TPU_REFS_PUSH",
        )
    }
    runs = {"off": [], "on": []}
    try:
        for _r in range(rounds):
            for mode in ("off", "on"):
                if mode == "off":
                    _os.environ["RAY_TPU_METRICS_PUSH_MS"] = "0"
                    _os.environ["RAY_TPU_REFS_PUSH"] = "0"
                    _os.environ.pop("RAY_TPU_TRACE", None)
                    _os.environ.pop("RAY_TPU_FLIGHT_DIR", None)
                    tracing.disable_tracing()
                else:
                    # The default push period, tracing on, flight dumps
                    # armed, refs-push feeding the object ledger — the
                    # whole plane, not a softened subset.
                    _os.environ["RAY_TPU_METRICS_PUSH_MS"] = "1000"
                    _os.environ["RAY_TPU_REFS_PUSH"] = "1"
                    _os.environ["RAY_TPU_TRACE"] = "1"
                    _os.environ["RAY_TPU_FLIGHT_DIR"] = flight_dir
                    tracing.enable_tracing()
                _config._reset_for_tests()
                ray_tpu.init(num_cpus=max(_os.cpu_count() or 1, 16))
                try:
                    ops = _multi_client_once()
                finally:
                    ray_tpu.shutdown()
                runs[mode].append(ops)
                print(
                    json.dumps({"mode": mode, "round": _r, "ops_per_s": ops}),
                    flush=True,
                )
    finally:
        for k, v in saved.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
        _config._reset_for_tests()
        tracing.disable_tracing()
    off_m = statistics.median(runs["off"])
    on_m = statistics.median(runs["on"])
    overhead_pct = round((off_m - on_m) / off_m * 100, 2)
    report = {
        "name": "telemetry_ab_multi_client_tasks_async",
        "note": (
            "interleaved OFF/ON rounds; medians compared (median-of-"
            f"{rounds}).  ON = RAY_TPU_METRICS_PUSH_MS=1000 + "
            "RAY_TPU_REFS_PUSH=1 (object-ledger ref tables) + "
            "RAY_TPU_TRACE=1 + flight recorder armed; OFF = push "
            "disabled, no refs push, no tracing, no flight dir"
        ),
        "off_runs": runs["off"],
        "on_runs": runs["on"],
        "off_median_ops_per_s": off_m,
        "on_median_ops_per_s": on_m,
        "overhead_pct": overhead_pct,
        "budget_pct": budget_pct,
        "pass": overhead_pct < budget_pct,
    }
    print(json.dumps(report, indent=1), flush=True)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    assert overhead_pct < budget_pct, (
        f"telemetry plane costs {overhead_pct}% on multi_client_tasks_async "
        f"(budget {budget_pct}%): off={runs['off']} on={runs['on']}"
    )
    return report


# ---------------------------------------------------------------------------
# io-shard sweep: the head-fabric scaling acceptance artifact


def shard_sweep(out_path=None, shard_counts=(0, 1, 2, 4), rounds: int = 3):
    """multi_client_tasks_async across RAY_TPU_HEAD_IO_SHARDS values:
    fresh cluster per point, median-of-N (the honesty rule), per-shard
    wire counters captured from the telemetry sink — the deterministic
    proof that decode work actually runs on shard pids.

        python -m ray_tpu._private.ray_perf --shard-sweep \
            [--json BENCH_shard_r1.json]

    Honesty note baked into the artifact: on a 1-vCPU host every shard
    process shares one core with the head, so throughput gains are
    bounded by core count — the sweep's job THERE is to show sharding
    costs ~nothing and moves the decode work out; scaling shows up on
    multi-core hosts (the reference envelope is 64 cores)."""
    import os as _os
    import statistics

    from ray_tpu._private import config as _config

    saved = _os.environ.get("RAY_TPU_HEAD_IO_SHARDS")
    sweep = []
    try:
        for n in shard_counts:
            _os.environ["RAY_TPU_HEAD_IO_SHARDS"] = str(n)
            _config._reset_for_tests()
            ray_tpu.init(num_cpus=max(_os.cpu_count() or 1, 16))
            runs = []
            shard_stats = {}
            try:
                for _ in range(rounds):
                    runs.append(_multi_client_once())
                from ray_tpu._private.runtime import get_runtime

                rt = get_runtime()
                time.sleep(1.3)  # let a final metrics push land
                for key, snap in sorted(rt.telemetry.processes.items()):
                    if not key.startswith("io_shard"):
                        continue
                    w = snap.get("wire") or {}
                    shard_stats[key] = {
                        "pid": snap.get("pid"),
                        "logical_frames": w.get("logical_frames", 0),
                        "physical_writes": w.get("physical_writes", 0),
                        "bytes_written": w.get("bytes_written", 0),
                        "conns": int(
                            (snap.get("internal") or {}).get("io_shard_conns", 0)
                        ),
                    }
            finally:
                ray_tpu.shutdown()
            rec = {
                "io_shards": n,
                "ops_per_s": round(statistics.median(runs), 1),
                "runs": runs,
                "shard_wire_stats": shard_stats,
            }
            sweep.append(rec)
            print(json.dumps(rec), flush=True)
    finally:
        if saved is None:
            _os.environ.pop("RAY_TPU_HEAD_IO_SHARDS", None)
        else:
            _os.environ["RAY_TPU_HEAD_IO_SHARDS"] = saved
        _config._reset_for_tests()
    report = {
        "name": "multi_client_tasks_async_shard_sweep",
        "host": host_shape(),
        "host_nproc": _os.cpu_count(),
        "note": (
            "median-of-%d per point, fresh cluster per point.  HONESTY: "
            "on a %s-vCPU host every io shard shares cores with the head "
            "process, so ops/s gains are bounded by core count — the "
            "meaningful claims here are (a) sharding at 0 extra cores "
            "costs within host noise and (b) shard_wire_stats proves the "
            "per-conn decode work (logical_frames/physical_writes) runs "
            "on shard pids, off the head's loop.  Throughput SCALING with "
            "shard count is a multi-core-host claim (reference envelope: "
            "32k tasks/s on 64 cores, SURVEY.md §6)."
            % (rounds, _os.cpu_count())
        ),
        "sweep": sweep,
    }
    print(json.dumps(report, indent=1), flush=True)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    return report


def host_copy_ceiling() -> Dict:
    """The host's raw copy envelope, measured: memcpy GB/s, single-stream
    loopback socket GB/s, and the relay integrity checksum's GB/s.  A
    broadcast's effective GB/s is bounded by these — on a 1-vCPU box
    whose memcpy runs ~1 GB/s, no transfer topology can land 300MB in
    under ~0.2s, and a checksummed relay hop costs about one extra
    memcpy of the object.  Stamped into BENCH artifacts so a number that
    looks far from the reference envelope carries its own explanation."""
    import os as _os
    import socket
    import threading
    import zlib

    mb = 100
    buf = _os.urandom(mb * 1024 * 1024)
    dst = bytearray(len(buf))
    t0 = time.perf_counter()
    dst[:] = buf
    memcpy = mb / 1024 / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    zlib.adler32(buf)
    adler = mb / 1024 / (time.perf_counter() - t0)
    a, b = socket.socketpair()
    view = memoryview(buf)

    def sender():
        off = 0
        while off < len(view):
            off += a.send(view[off : off + (1 << 20)])
        a.close()

    th = threading.Thread(target=sender, daemon=True)
    recv = memoryview(dst)
    t0 = time.perf_counter()
    th.start()
    got = 0
    while got < len(buf):
        n = b.recv_into(recv[got:], len(buf) - got)
        if n == 0:
            break
        got += n
    loopback = mb / 1024 / (time.perf_counter() - t0)
    b.close()
    th.join(5)
    return {
        "name": "host_copy_ceiling",
        "memcpy_gb_per_s": round(memcpy, 2),
        "adler32_gb_per_s": round(adler, 2),
        "loopback_stream_gb_per_s": round(loopback, 2),
    }


def _cold_broadcast_once(rt, nids, payload, land, expect) -> float:
    """One COLD broadcast round: fresh put (new object id), land on every
    target node, free.  Returns the wall seconds of the landing wave."""
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    ref = ray_tpu.put(payload)
    t0 = time.perf_counter()
    outs = ray_tpu.get(
        [
            land.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(nid)
            ).remote(ref)
            for nid in nids
        ],
        timeout=300,
    )
    dt = time.perf_counter() - t0
    assert all(o == expect for o in outs)
    del ref  # free the copies before the next cold round
    return dt


def _set_relay(enabled: bool) -> None:
    import os as _os

    from ray_tpu._private import config as _config

    _os.environ["RAY_TPU_RELAY_PIPELINE"] = "1" if enabled else "0"
    _config._reset_for_tests()


def broadcast_relay_ab(rt, nids, mb: int = 100, rounds: int = 3) -> Dict:
    """INTERLEAVED relay on/off A/B of the cold broadcast (same cluster,
    same payload size, alternating rounds): the acceptance measurement
    for the pipelined transfer plan.  The OFF side is the classic
    staggered admission (BENCH_objmem_r1's regime); the ON side hands
    out chain/tree plans with mid-flight relays.  Counter leg: the ON
    rounds must land EXACTLY one sealed copy (pull|relay) per receiving
    node per round — pipelining must not multiply copies or re-read the
    source."""
    import numpy as np
    import statistics

    payload = np.random.default_rng(1).integers(
        0, 255, size=mb * 1024 * 1024, dtype=np.uint8
    )
    expect = int(payload[::1024].sum())

    @ray_tpu.remote
    def land(x):
        return int(x[::1024].sum())

    total_gb = mb * len(nids) / 1024
    times = {"on": [], "off": []}
    try:
        _set_relay(True)  # warm both regimes once (worker spawn etc.)
        _cold_broadcast_once(rt, nids, payload, land, expect)
        time.sleep(1.0)
        c0 = _cluster_copy_stats()
        on_rounds = 0
        for _ in range(rounds):
            for side in ("on", "off"):
                _set_relay(side == "on")
                times[side].append(
                    round(_cold_broadcast_once(rt, nids, payload, land, expect), 3)
                )
                if side == "on":
                    on_rounds += 1
                time.sleep(0.3)  # let frees land before the next cold round
        _set_relay(True)
        time.sleep(1.5)  # final worker copy-counter pushes land
        c1 = _cluster_copy_stats()
    finally:
        _set_relay(True)
    stats = _copy_stats_delta(c0, c1)
    landed = sum(
        stats.get(p, {}).get("copies", 0) for p in ("pull", "relay")
    )
    on = statistics.median(times["on"])
    off = statistics.median(times["off"])
    return {
        "name": f"broadcast_relay_ab_{mb}mb_to_{len(nids)}_nodes",
        "note": (
            "single-host A/B: all 'nodes' share one CPU, so both regimes "
            "are bound by the host_copy_ceiling (every relay hop adds one "
            "adler32 pass ~= a memcpy of the object) and the pipeline's "
            "structural win — replacing log2(N) serial whole-object "
            "rounds with one concurrent chain — cannot show in wall "
            "clock; the relay counters + plan shape are the claim this "
            "artifact proves, the multi-host wall-clock claim needs "
            "multi-host hardware (same residual class as BENCH_shard_r2)"
        ),
        "rounds": rounds,
        "relay_on_s": times["on"],
        "relay_off_s": times["off"],
        "on_median_s": on,
        "off_median_s": off,
        "on_gb_per_s": round(total_gb / on, 2),
        "off_gb_per_s": round(total_gb / off, 2),
        "speedup": round(off / on, 2),
        # one sealed copy per receiving node per timed round (warm round
        # + A/B off-rounds included in the window: every cold round of
        # EITHER regime lands exactly n_nodes copies)
        "copies_per_round": round(landed / max(2 * rounds + 1, 1), 2),
        "nodes": len(nids),
        "copy_stats": stats,
    }


def broadcast_sweep(rt, sizes_mb=(8, 100), fanouts=(2, 4),
                    chunks_mb=(1, 8), rounds: int = 3) -> Dict:
    """Cold-broadcast grid: object size x fan-out (receiving nodes) x
    transfer chunk size, median-of-N cold rounds per cell, relay plans
    on.  The effective GB/s figure is (size * fanout) / wall — bytes
    landed per second of broadcast wall clock.  Daemons resolve the
    chunk knob at spawn, so each chunk size gets a FRESH node set (env
    inherited at daemon launch)."""
    import os as _os
    import statistics

    import numpy as np

    from ray_tpu._private import config as _config
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray_tpu.remote
    def land(x):
        return int(x[::1024].sum())

    @ray_tpu.remote
    def warm():
        return 1

    cells = []
    saved = _os.environ.get("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES")
    try:
        for chunk_mb in chunks_mb:
            _os.environ["RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES"] = str(
                chunk_mb * 1024 * 1024
            )
            _config._reset_for_tests()
            nids = [rt.add_daemon_node(num_cpus=1) for _ in range(max(fanouts))]
            ray_tpu.get(
                [
                    warm.options(
                        scheduling_strategy=NodeAffinitySchedulingStrategy(nid)
                    ).remote()
                    for nid in nids
                ],
                timeout=120,
            )
            for mb in sizes_mb:
                payload = np.random.default_rng(mb).integers(
                    0, 255, size=mb * 1024 * 1024, dtype=np.uint8
                )
                expect = int(payload[::1024].sum())
                for fanout in fanouts:
                    runs = [
                        round(
                            _cold_broadcast_once(
                                rt, nids[:fanout], payload, land, expect
                            ),
                            3,
                        )
                        for _ in range(rounds)
                    ]
                    med = statistics.median(runs)
                    cells.append(
                        {
                            "mb": mb,
                            "fanout": fanout,
                            "chunk_mb": chunk_mb,
                            "cold_s": runs,
                            "median_s": med,
                            "gb_per_s": round(mb * fanout / 1024 / med, 2),
                        }
                    )
                    time.sleep(0.3)
            for nid in nids:
                rt.remove_node(nid)
    finally:
        if saved is None:
            _os.environ.pop("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", None)
        else:
            _os.environ["RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES"] = saved
        _config._reset_for_tests()
    return {
        "name": "broadcast_sweep",
        "note": "relay plans ON; gb_per_s = size*fanout/wall (median-of-%d); "
        "fresh daemons per chunk size (the knob binds at spawn)" % rounds,
        "cells": cells,
    }


def arena_put_get_ab(rounds: int = 3, chunk_mb: int = 100, n_chunks: int = 5) -> Dict:
    """Arena vs file-per-object A/B for the hot put/get path: fresh
    cluster per side per round (the store backend is fixed at init),
    interleaved.  Counter leg: BOTH backends must show exactly one
    sealed copy per put (create->seal is one copy); the arena side must
    additionally show one zero-byte arena_map per get — reads MAP the
    sealed buffer, they don't copy it out of the store."""
    import os as _os
    import statistics

    import numpy as np

    from ray_tpu._private import config as _config
    from ray_tpu._private import telemetry as _telemetry

    chunk = np.zeros(chunk_mb * 1024 * 1024, dtype=np.uint8)
    gb = chunk_mb * n_chunks / 1024
    out = {"arena": {"runs": []}, "file": {"runs": []}}
    saved = _os.environ.get("RAY_TPU_NATIVE_STORE")
    try:
        for _ in range(rounds):
            for side in ("arena", "file"):
                _os.environ["RAY_TPU_NATIVE_STORE"] = (
                    "1" if side == "arena" else "0"
                )
                _config._reset_for_tests()
                ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
                try:
                    refs = [ray_tpu.put(chunk) for _ in range(1)]  # warm
                    ray_tpu.get(refs)
                    del refs
                    c0 = _telemetry.copy_counter_snapshot()
                    t0 = time.perf_counter()
                    refs = [ray_tpu.put(chunk) for _ in range(n_chunks)]
                    for r in refs:
                        v = ray_tpu.get(r, timeout=120)
                        assert v.nbytes == chunk.nbytes
                    dt = time.perf_counter() - t0
                    del refs, v
                    stats = _copy_stats_delta(
                        c0, _telemetry.copy_counter_snapshot()
                    )
                    out[side]["runs"].append(round(gb / dt, 2))
                    out[side]["copy_stats"] = stats
                finally:
                    ray_tpu.shutdown()
    finally:
        if saved is None:
            _os.environ.pop("RAY_TPU_NATIVE_STORE", None)
        else:
            _os.environ["RAY_TPU_NATIVE_STORE"] = saved
        _config._reset_for_tests()
    for side in ("arena", "file"):
        out[side]["gb_per_s"] = statistics.median(out[side]["runs"])
    return {
        "name": "arena_put_get_ab",
        "note": (
            "interleaved fresh-cluster A/B; gb_per_s is put+get of "
            f"{gb:.2f}GB counted once, median-of-{rounds}.  copy_stats "
            "(last round) prove 1 put-copy per put on both sides and "
            "zero-byte arena_map reads on the arena side"
        ),
        **out,
        "arena_over_file": round(
            out["arena"]["gb_per_s"] / max(out["file"]["gb_per_s"], 1e-9), 3
        ),
    }


def object_plane_bench(out_path=None):
    """The object-plane fast-path benchmark (ISSUE 12): put throughput,
    the arena put/get A/B, the relay on/off broadcast A/B (acceptance:
    cold 100MB x 3-node >= 3x the staggered baseline), and the broadcast
    sweep (size x fan-out x chunk), all with bytes-per-copy counter
    deltas.

        python -m ray_tpu._private.ray_perf --object-plane \
            [--json BENCH_objmem_r2.json]
    """
    import os as _os

    results = [{"name": "host_note", **host_shape()}, host_copy_ceiling()]
    print(json.dumps(results[-1]), flush=True)
    # Arena A/B boots its own clusters: run it FIRST (clean slate).
    r = arena_put_get_ab()
    results.append(r)
    print(json.dumps(r), flush=True)
    ray_tpu.init(num_cpus=max(_os.cpu_count() or 1, 8), ignore_reinit_error=True)
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    results.append(bench_put_gigabytes())
    print(json.dumps(results[-1]), flush=True)
    nids = [rt.add_daemon_node(num_cpus=1) for _ in range(4)]

    @ray_tpu.remote
    def warm():
        return 1

    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    ray_tpu.get(
        [
            warm.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(nid)
            ).remote()
            for nid in nids
        ],
        timeout=120,
    )
    r = broadcast_relay_ab(rt, nids[:3])
    results.append(r)
    print(json.dumps(r), flush=True)
    for nid in nids:
        rt.remove_node(nid)
    r = broadcast_sweep(rt)
    results.append(r)
    print(json.dumps(r), flush=True)
    ray_tpu.shutdown()
    report = {
        "name": "object_plane_fastpath",
        "note": (
            "relay A/B is interleaved on/off on one cluster (off = the "
            "classic staggered rounds, BENCH_objmem_r1's regime); "
            "broadcast gb_per_s = size*fanout/wall; copy_stats are "
            "object_copies/object_copy_bytes counter deltas (cluster "
            "aggregate for broadcasts, this process for puts)"
        ),
        "benches": results,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    return report


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    out_path = None
    if "--json" in argv:
        out_path = argv[argv.index("--json") + 1]
    if "--telemetry-ab" in argv:
        return telemetry_ab(out_path)
    if "--refs-ab" in argv:
        return refs_ab(out_path)
    if "--prof-ab" in argv:
        return prof_ab(out_path)
    if "--shard-sweep" in argv:
        return shard_sweep(out_path)
    if "--object-plane" in argv:
        return object_plane_bench(out_path)
    if "--io-shards" in argv:
        # Whole-suite override: run every bench with a sharded head
        # fabric (the env form reaches the Runtime this process boots).
        import os as _os2

        _os2.environ["RAY_TPU_HEAD_IO_SHARDS"] = argv[
            argv.index("--io-shards") + 1
        ]
        from ray_tpu._private import config as _config2

        _config2._reset_for_tests()
    import os as _os

    # Logical-CPU headroom: the benches measure control-plane throughput,
    # not core count; without it a small host can't place the n:n actor
    # pairs at all (the reference runs these on 64-core machines).
    ray_tpu.init(num_cpus=max(_os.cpu_count() or 1, 16), ignore_reinit_error=True)
    if _os.environ.get("RAY_TPU_PERF_PERSIST") == "1":
        _enable_local_persistence()
    results = [
        {
            "name": "host_note",
            **host_shape(),
            "note": (
                "ops_per_s is the MEDIAN of the 3 runs ('runs' lists all); "
                "writes_per_op / frames_per_op are this process's wire-"
                "counter deltas (physical writes vs logical control frames "
                "per op — the frame-coalescing factor); with "
                "RAY_TPU_PERF_PERSIST=1 journal_appends_per_op / "
                "journal_fsyncs_per_op report the GCS mutation journal's "
                "per-op durability cost the same way"
            ),
        }
    ]
    # --profile: the whole suite runs with the cluster profiler hot; the
    # output gains a merged flamegraph (top stacks) + the stage-attributed
    # task summary, so any bench shape ships with "where the time went"
    # evidence instead of a bare ops/s number (ISSUE 10).
    profiling = "--profile" in argv
    if profiling:
        from ray_tpu.util import state as _state_api

        _state_api.profile_start()
    for bench in ALL:
        r = bench()
        results.append(r)
        print(json.dumps(r), flush=True)
    if profiling:
        import time as _t

        from ray_tpu.util import state as _state_api

        _state_api.profile_stop()
        _t.sleep(1.2)  # final worker prof_push beats land
        rep = _state_api.profile_report()
        top = sorted(
            (rep.get("samples") or {}).items(), key=lambda kv: -kv[1]
        )[:25]
        prof_result = {
            "name": "profile_attachment",
            "pids": rep.get("pids"),
            "total_samples": rep.get("total_samples"),
            "top_stacks": [{"stack": s, "samples": n} for s, n in top],
            "task_summary": {
                k: v
                for k, v in _state_api.task_summary(slow=5).items()
                if k in (
                    "tasks", "states", "stages", "accounted_fraction",
                    "slow",
                )
            },
        }
        results.append(prof_result)
        print(json.dumps(prof_result), flush=True)
    ray_tpu.shutdown()
    if out_path:
        with open(out_path, "w") as f:
            json.dump(results, f, indent=1)
    return results




def bench_broadcast_cross_node(n_nodes: int = 3, mb: int = 100) -> Dict:
    """Broadcast one large object to N ISOLATED-store daemon nodes over the
    transfer plane (BASELINE.md: '1 GiB broadcast to 50 nodes' scalability
    row; here sized for CI).  Each node pulls chunked from the owner and
    seals a local copy — no shared filesystem path involved."""
    import numpy as np

    from ray_tpu._private.runtime import get_runtime
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    rt = get_runtime()
    nids = [rt.add_daemon_node(num_cpus=1) for _ in range(n_nodes)]
    payload = np.random.default_rng(0).integers(
        0, 255, size=mb * 1024 * 1024, dtype=np.uint8
    )
    ref = ray_tpu.put(payload)

    @ray_tpu.remote
    def land(x):
        return int(x[::1024].sum())

    @ray_tpu.remote
    def warm_up():
        return 1

    # Spawn each node's worker BEFORE the timed run: the cold number must
    # measure the transfer plane, not process boot.
    ray_tpu.get(
        [
            warm_up.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(nid)
            ).remote()
            for nid in nids
        ],
        timeout=120,
    )

    expect = int(payload[::1024].sum())

    def run():
        t0 = time.perf_counter()
        outs = ray_tpu.get(
            [
                land.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(nid)
                ).remote(ref)
                for nid in nids
            ],
            timeout=300,
        )
        assert all(o == expect for o in outs)
        return time.perf_counter() - t0

    # Copy counters are cluster-wide (each node's worker counts its own
    # pull): snapshot the pushed aggregate around the cold round, with a
    # settle sleep so the final worker ticks land.
    time.sleep(1.5)
    c0 = _cluster_copy_stats()
    cold = run()  # every node pulls over the wire
    time.sleep(1.5)
    c1 = _cluster_copy_stats()
    warm = run()  # all copies local: pure read path
    for nid in nids:
        rt.remove_node(nid)
    total_gb = mb * n_nodes / 1024
    return {
        "name": f"broadcast_{mb}mb_to_{n_nodes}_nodes",
        "cold_s": round(cold, 3),
        "cold_gb_per_s": round(total_gb / cold, 2),
        "warm_s": round(warm, 3),
        # the bytes-per-copy ledger of the cold broadcast: n_nodes pull
        # copies of the packed payload, and nothing else should move
        "copy_stats": _copy_stats_delta(c0, c1),
    }


if __name__ == "__main__":
    main()
