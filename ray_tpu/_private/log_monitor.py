"""Log pipeline: per-process log files + tail-to-driver streaming.

ray: python/ray/_private/log_monitor.py:104 — each node runs a monitor
that tails the session's worker log files and publishes new lines; the
driver subscribes and prints them prefixed.  Same shape here:

  * every worker's stdout/stderr is redirected AT SPAWN into
    `<log_dir>/worker-<wid>.out|.err` on its own node (the file outlives
    the worker — crash output is never lost);
  * a LogMonitor thread on each node (driver process for head workers,
    node daemon for its pool) tails those files and forwards fresh lines;
  * daemon monitors forward over the daemon conn as ("log_lines", wid,
    stream, lines); the driver prints every line as
    `(worker-<wid> .err) line` and keeps a bounded ring buffer per worker
    backing `ray_tpu logs` / the dashboard's /api/logs.

Rate limiting: at most `max_lines_per_poll` lines per file per tick ride
the wire; a flood is truncated with a marker line rather than stalling the
control conn (ray: log_monitor's RATE_LIMIT semantics).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Tuple


def worker_log_paths(log_dir: str, wid: str) -> Tuple[str, str]:
    return (
        os.path.join(log_dir, f"worker-{wid}.out"),
        os.path.join(log_dir, f"worker-{wid}.err"),
    )


def open_worker_logs(log_dir: str, wid: str):
    """(stdout_file, stderr_file) ready to hand to Popen."""
    os.makedirs(log_dir, exist_ok=True)
    out_path, err_path = worker_log_paths(log_dir, wid)
    return open(out_path, "ab", buffering=0), open(err_path, "ab", buffering=0)


class LogMonitor:
    """Tails worker-*.out/.err files in one directory.

    sink(wid, stream, lines) is called with decoded, newline-stripped
    fresh lines; `stream` is "out" or "err".
    """

    MAX_LINES_PER_POLL = 200

    def __init__(
        self,
        log_dir: str,
        sink: Callable[[str, str, List[str]], None],
        poll_interval: float = 0.15,
    ):
        self.log_dir = log_dir
        self.sink = sink
        self.poll_interval = poll_interval
        self._offsets: Dict[str, int] = {}  # path -> bytes consumed
        self._partial: Dict[str, bytes] = {}  # path -> trailing unterminated bytes
        self._stop = threading.Event()
        # flush() may run from the shutdown thread while the monitor thread
        # is mid-poll: serialize, or both deliver the same bytes twice.
        self._poll_lock = threading.Lock()
        # Files that predate this monitor belong to a PREVIOUS incarnation
        # (head restart over the same session log dir): start them at EOF —
        # replaying the whole history to stdout is noise, and the bytes are
        # still in the files for `ray_tpu logs`.
        if os.path.isdir(log_dir):
            for name in os.listdir(log_dir):
                path = os.path.join(log_dir, name)
                try:
                    self._offsets[path] = os.path.getsize(path)
                except OSError:
                    pass
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="raytpu-logmon"
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.poll_once()
            except Exception:
                pass  # a vanished file mid-scan is routine

    def poll_once(self) -> None:
        with self._poll_lock:
            self._poll_once_locked()

    def _poll_once_locked(self) -> None:
        if not os.path.isdir(self.log_dir):
            return
        for name in sorted(os.listdir(self.log_dir)):
            if not name.startswith("worker-"):
                continue
            stem, _, ext = name.rpartition(".")
            if ext not in ("out", "err"):
                continue
            wid = stem[len("worker-") :]
            path = os.path.join(self.log_dir, name)
            self._drain_file(path, wid, ext)

    def _drain_file(self, path: str, wid: str, stream: str) -> None:
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        offset = self._offsets.get(path, 0)
        if size <= offset:
            return
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(size - offset)
        except OSError:
            return
        self._offsets[path] = size
        data = self._partial.pop(path, b"") + data
        lines = data.split(b"\n")
        if lines and lines[-1]:
            self._partial[path] = lines[-1]  # unterminated tail: hold it
        lines = lines[:-1]
        if not lines:
            return
        dropped = 0
        if len(lines) > self.MAX_LINES_PER_POLL:
            dropped = len(lines) - self.MAX_LINES_PER_POLL
            lines = lines[: self.MAX_LINES_PER_POLL]
        decoded = [ln.decode("utf-8", "replace") for ln in lines]
        if dropped:
            decoded.append(f"... {dropped} lines rate-limited by log monitor ...")
        self.sink(wid, stream, decoded)

    def stop(self) -> None:
        self._stop.set()

    def flush(self) -> None:
        """One synchronous drain (shutdown path: don't lose final lines)."""
        try:
            self.poll_once()
        except Exception:
            pass
