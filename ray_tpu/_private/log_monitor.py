"""Log pipeline: per-process log files + tail-to-driver streaming.

ray: python/ray/_private/log_monitor.py:104 — each node runs a monitor
that tails the session's worker log files and publishes new lines; the
driver subscribes and prints them prefixed.  Same shape here:

  * every worker's stdout/stderr is redirected AT SPAWN into
    `<log_dir>/worker-<wid>.out|.err` on its own node (the file outlives
    the worker — crash output is never lost);
  * a LogMonitor thread on each node (driver process for head workers,
    node daemon for its pool) tails those files and forwards fresh lines;
  * daemon monitors forward over the daemon conn as ("log_lines", wid,
    stream, lines); the driver prints every line as
    `(worker-<wid> .err) line` and keeps a bounded ring buffer per worker
    backing `ray_tpu logs` / the dashboard's /api/logs.

Rate limiting: at most `max_lines_per_poll` lines per file per tick ride
the wire; a flood is truncated with a marker line rather than stalling the
control conn (ray: log_monitor's RATE_LIMIT semantics).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Tuple


def format_log_lines(wid: str, stream: str, lines) -> str:
    """The one spelling of the driver-facing log prefix (head echo AND
    attached-driver streaming use it — they must not drift)."""
    prefix = f"({wid}" + (" .err) " if stream == "err" else ") ")
    return "".join(prefix + ln + "\n" for ln in lines)


def worker_log_paths(log_dir: str, wid: str) -> Tuple[str, str]:
    return (
        os.path.join(log_dir, f"worker-{wid}.out"),
        os.path.join(log_dir, f"worker-{wid}.err"),
    )


def open_worker_logs(log_dir: str, wid: str):
    """(stdout_file, stderr_file) ready to hand to Popen."""
    os.makedirs(log_dir, exist_ok=True)
    out_path, err_path = worker_log_paths(log_dir, wid)
    return open(out_path, "ab", buffering=0), open(err_path, "ab", buffering=0)


class LogMonitor:
    """Tails worker-*.out/.err files in one directory.

    sink(wid, stream, lines) is called with decoded, newline-stripped
    fresh lines; `stream` is "out" or "err".
    """

    MAX_LINES_PER_POLL = 200

    def __init__(
        self,
        log_dir: str,
        sink: Callable[[str, str, List[str]], None],
        poll_interval: float = 0.15,
    ):
        self.log_dir = log_dir
        self.sink = sink
        self.poll_interval = poll_interval
        self._offsets: Dict[str, int] = {}  # path -> bytes consumed
        self._partial: Dict[str, bytes] = {}  # path -> trailing unterminated bytes
        self._known: Dict[str, tuple] = {}  # name -> (path, wid, ext)
        self._active_until: Dict[str, float] = {}  # path -> active deadline
        self._last_scan = 0.0
        self._tick = 0
        self._stop = threading.Event()
        # flush() may run from the shutdown thread while the monitor thread
        # is mid-poll: serialize, or both deliver the same bytes twice.
        self._poll_lock = threading.Lock()
        # Files that predate this monitor belong to a PREVIOUS incarnation
        # (head restart over the same session log dir): start them at EOF —
        # replaying the whole history to stdout is noise, and the bytes are
        # still in the files for `ray_tpu logs`.
        if os.path.isdir(log_dir):
            for name in os.listdir(log_dir):
                path = os.path.join(log_dir, name)
                try:
                    self._offsets[path] = os.path.getsize(path)
                except OSError:
                    pass
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="raytpu-logmon"
        )
        self._thread.start()

    # Quiet files back off: a file unchanged for ACTIVE_WINDOW_S drops to
    # one stat per DORMANT_EVERY ticks.  At 1000 live-but-silent workers
    # the per-tick scan was a measured ~5.7k stat()/s of pure overhead on
    # the head (ray: log_monitor.py has the same open-file LRU problem and
    # solves it with a bounded open-file set).
    ACTIVE_WINDOW_S = 5.0
    DORMANT_EVERY = 10
    RESCAN_INTERVAL_S = 0.5

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.poll_once()
            except Exception:
                pass  # a vanished file mid-scan is routine

    def poll_once(self, force: bool = False) -> None:
        with self._poll_lock:
            self._poll_once_locked(force)

    def _rescan(self, now: float) -> None:
        if not os.path.isdir(self.log_dir):
            return
        known = self._known
        for name in os.listdir(self.log_dir):
            if name.startswith("worker-") and name not in known:
                stem, _, ext = name.rpartition(".")
                if ext in ("out", "err"):
                    path = os.path.join(self.log_dir, name)
                    known[name] = (path, stem[len("worker-"):], ext)
                    # A just-created file is the MOST likely to speak next
                    # (boot output, crash tracebacks): start it active.
                    self._active_until[path] = now + self.ACTIVE_WINDOW_S

    def _poll_once_locked(self, force: bool = False) -> None:
        import time as _time

        now = _time.monotonic()
        if now - self._last_scan >= self.RESCAN_INTERVAL_S or force:
            self._last_scan = now
            self._rescan(now)
        self._tick += 1
        check_dormant = force or (self._tick % self.DORMANT_EVERY == 0)
        for path, wid, ext in list(self._known.values()):
            if not check_dormant and now >= self._active_until.get(path, 0.0):
                continue
            if self._drain_file(path, wid, ext):
                self._active_until[path] = now + self.ACTIVE_WINDOW_S

    def _drain_file(self, path: str, wid: str, stream: str) -> bool:
        """Returns True when fresh bytes were consumed (activity signal)."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return False
        offset = self._offsets.get(path, 0)
        if size <= offset:
            return False
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(size - offset)
        except OSError:
            return False
        self._offsets[path] = size
        data = self._partial.pop(path, b"") + data
        lines = data.split(b"\n")
        if lines and lines[-1]:
            self._partial[path] = lines[-1]  # unterminated tail: hold it
        lines = lines[:-1]
        if not lines:
            return True
        dropped = 0
        if len(lines) > self.MAX_LINES_PER_POLL:
            dropped = len(lines) - self.MAX_LINES_PER_POLL
            lines = lines[: self.MAX_LINES_PER_POLL]
        decoded = [ln.decode("utf-8", "replace") for ln in lines]
        if dropped:
            decoded.append(f"... {dropped} lines rate-limited by log monitor ...")
        self.sink(wid, stream, decoded)
        return True

    def stop(self) -> None:
        self._stop.set()

    def flush(self) -> None:
        """One synchronous drain (shutdown path: don't lose final lines)."""
        try:
            self.poll_once(force=True)
        except Exception:
            pass
