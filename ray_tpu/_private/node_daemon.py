"""Node daemon: the per-host worker-pool + object-store process (raylet-lite).

ray: src/ray/raylet/main.cc + node_manager.h:115 — one daemon per host owns
that host's worker processes.  TPU-first simplification: scheduling and
ownership stay with the driver (single-controller); the daemon's jobs are
  * process supervision on its host — spawn workers on request, kill them
    on request, and take the whole pool down with it when it dies (node
    failure); workers connect DIRECTLY to the driver over TCP (the direct
    task transport, ray: direct_task_transport.h:75);
  * the NODE OBJECT STORE — an isolated per-node shm directory (no path is
    shared across nodes) that this daemon creates, its workers seal results
    into, and its ObjectServer serves to other nodes over the transfer
    plane (ray: the plasma store + object manager attached to each raylet,
    src/ray/object_manager/object_manager.h:117).

Launch:  python -m ray_tpu._private.node_daemon
with env RAY_TPU_DRIVER_HOST/PORT, RAY_TPU_AUTHKEY, RAY_TPU_NODE_CONFIG
(json: node_id, num_cpus, resources, labels, session, store_root?).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
from typing import Dict


def _peer_host() -> str:
    from ray_tpu._private import config as _config

    return _config.get("node_ip")


def _apply_pythonpath(env: Dict[str, str]) -> None:
    """Stamp PYTHONPATH so children resolve ray_tpu + the daemon's own
    module search path (one implementation: worker env AND zygote env)."""
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    paths = [pkg_root] + [p for p in sys.path if p] + (
        env.get("PYTHONPATH", "").split(os.pathsep) if env.get("PYTHONPATH") else []
    )
    env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(paths))


def _build_worker_env(
    wid: str, host: str, port: int, authkey_hex: str, session: str, renv,
    store_dir: str, node_id: str,
) -> Dict[str, str]:
    from ray_tpu._private.runtime_env import worker_env_entries

    renv = renv or {}
    env_vars = renv.get("env_vars") or {}
    env = os.environ.copy()
    env.update(
        {
            "RAY_TPU_DRIVER_HOST": host,
            "RAY_TPU_DRIVER_PORT": str(port),
            "RAY_TPU_AUTHKEY": authkey_hex,
            "RAY_TPU_WORKER_ID": wid,
            "RAY_TPU_SESSION": session,
            # Log files are block-buffered without this: prints must land
            # promptly for the monitor to forward them.
            "PYTHONUNBUFFERED": "1",
            # This node's store, NOT the session default: workers seal into
            # and read from their own node's directory only.
            "RAY_TPU_STORE_DIR": store_dir,
            # Node identity rides the worker's "ready" handshake so a
            # restarted head can adopt the worker back onto this node.
            "RAY_TPU_NODE_ID": node_id,
            # Peer-transport advertise host: this NODE's address (the
            # worker's direct-call listener must be reachable from other
            # nodes' workers), not the head's.
            "RAY_TPU_PEER_HOST": _peer_host(),
            **worker_env_entries(renv),
        }
    )
    env.update({k: str(v) for k, v in env_vars.items()})
    # Workers must die with their daemon even on SIGKILL (a raylet's workers
    # don't outlive node death): worker_main arms PR_SET_PDEATHSIG.
    env["RAY_TPU_PDEATHSIG"] = "1"
    _apply_pythonpath(env)
    return env


def main() -> None:
    from multiprocessing.connection import Client

    host = os.environ["RAY_TPU_DRIVER_HOST"]
    port = int(os.environ["RAY_TPU_DRIVER_PORT"])
    authkey_hex = os.environ["RAY_TPU_AUTHKEY"]
    cfg = json.loads(os.environ["RAY_TPU_NODE_CONFIG"])
    node_id = cfg["node_id"]
    session = cfg["session"]
    from ray_tpu._private import faults, telemetry

    faults.set_process_tag(f"daemon:{node_id}")
    telemetry.install(f"daemon:{node_id}")

    # The node object store: an isolated per-node directory (distinct even
    # when several daemons share one machine in tests — no cross-node path
    # sharing), created HERE so the arena exists before any worker joins.
    from ray_tpu._private import config as _config
    from ray_tpu._private.object_plane import ObjectServer
    from ray_tpu._private.store import ShmStore, _default_capacity, _default_shm_root

    store_root = cfg.get("store_root") or _default_shm_root()
    store_dir = os.path.join(store_root, f"raytpu-{session}-{node_id}")
    capacity = _config.get("object_store_memory") or _default_capacity(store_root)
    store = ShmStore(session, capacity=capacity, dir_path=store_dir)
    authkey = bytes.fromhex(authkey_hex)
    # read_board: the pipelined-broadcast relay path — this server streams
    # the landed prefix of a pull still in flight in one of this node's
    # workers (the board file in the shared store dir carries progress).
    obj_server = ObjectServer(
        store.get_raw, authkey, advertise_host=_config.get("node_ip"),
        read_board=store.read_board,
    )
    # The node arena's fd, held open for handoff to workers: the zygote
    # gets it over its AF_UNIX pipe (SCM_RIGHTS, netutil.send_fd) and
    # forked workers inherit it; directly-spawned workers inherit via
    # pass_fds.  A worker that cannot map the fd falls back to the path,
    # then to the file-per-object store (store.py arena.map fallback).
    arena_fd = None
    if store.arena is not None:
        try:
            arena_fd = os.open(store.arena.path, os.O_RDWR)
        except OSError:
            arena_fd = None
    # This node's log dir: workers' stdout/stderr land here; the monitor
    # below tails the files and forwards fresh lines to the head
    # (ray: per-node log_monitor.py publishing to the driver).
    log_dir = f"/tmp/raytpu-logs-{session}-{node_id}"
    send_lock = threading.Lock()

    from ray_tpu._private import wire
    from ray_tpu._private.netutil import set_nodelay

    def connect():
        # Batching sender: heartbeats piggyback on whatever log_lines /
        # worker_exited frames are pending — one physical write per loop
        # tick instead of one per message (the flush sits right before
        # the loop's blocking wait).
        c = wire.batching(wire.connect((host, port), authkey))
        set_nodelay(c)
        import time as _t

        c.send(
            (
                "daemon",
                node_id,
                {
                    "num_cpus": cfg.get("num_cpus", 1.0),
                    "resources": cfg.get("resources") or {},
                    "labels": cfg.get("labels") or {},
                    "object_endpoint": obj_server.endpoint,
                    # Clock-offset sample for the head's merged timeline
                    # (same estimate the worker ready hello carries).
                    "clock": _t.time(),
                },
                os.getpid(),
            )
        )
        c.flush()  # the head's handshake thread is waiting on this hello
        return c

    def reconnect():
        """Head conn lost: in head-split mode, retry the head's fixed
        address for the window (a restarted head re-registers this node);
        None = give up (classic mode or window expired) -> node death."""
        import time as _time

        window = _config.get("reconnect_window_s")
        if window <= 0:
            return None
        deadline = _time.monotonic() + window
        while _time.monotonic() < deadline:
            try:
                return connect()
            except Exception:
                _time.sleep(0.5)
        return None

    conn = connect()

    def forward_logs(wid, stream, lines):
        try:
            with send_lock:
                conn.send(("log_lines", wid, stream, lines))
        except OSError:
            pass  # head away (restart window); lines stay in the files

    from ray_tpu._private.log_monitor import LogMonitor, open_worker_logs

    log_monitor = LogMonitor(log_dir, forward_logs)

    children: Dict[str, subprocess.Popen] = {}
    spawn_ts: Dict[str, float] = {}
    # Zygote fork server for this node's workers (zygote.py): ~2ms forks
    # from a pre-imported interpreter instead of ~250ms interpreter boots
    # — and forked workers inherit numpy/cloudpickle already imported, so
    # a cold broadcast pull doesn't pay a numpy import inside the
    # unpickle (measured ~0.9s per worker on a contended host).
    zyg: Dict[str, object] = {"conn": None, "proc": None, "env": None}
    zpids: Dict[str, int] = {}  # zygote-forked wid -> pid

    def start_zygote() -> None:
        if not _config.get("use_zygote"):
            return
        from multiprocessing.connection import Pipe

        parent, child = Pipe()
        env = os.environ.copy()
        env.pop("PALLAS_AXON_POOL_IPS", None)  # no jax in the zygote
        env["PYTHONUNBUFFERED"] = "1"
        env["RAY_TPU_ZYGOTE_FD"] = str(child.fileno())
        _apply_pythonpath(env)
        try:
            p = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.zygote"],
                env=env,
                pass_fds=[child.fileno()],
                close_fds=True,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        except OSError:
            parent.close()
            child.close()
            return
        child.close()
        zyg["conn"] = wire.wrap(parent)
        zyg["proc"] = p
        zyg["env"] = env
        # Hand the node arena's open fd to the zygote over this AF_UNIX
        # pipe (SCM_RIGHTS): the frame announces it, the ancillary
        # message carries it, and every forked worker inherits the
        # descriptor (the zygote stamps RAY_TPU_ARENA_FD with ITS fd
        # number).  Failure is non-fatal — workers fall back to opening
        # the arena by path.
        if arena_fd is not None:
            from ray_tpu._private import netutil

            try:
                zyg["conn"].send(("arena_fd", store.arena.path))
                netutil.send_fd(zyg["conn"], arena_fd, p.pid)
            except (OSError, ValueError):
                pass

    def zygote_fork(wid: str, full_env: Dict[str, str]) -> bool:
        zc = zyg["conn"]
        if zc is None:
            return False
        base = zyg["env"] or {}
        overrides = {k: v for k, v in full_env.items() if base.get(k) != v}
        from ray_tpu._private.log_monitor import worker_log_paths

        os.makedirs(log_dir, exist_ok=True)
        out_path, err_path = worker_log_paths(log_dir, wid)
        try:
            zc.send(("fork", wid, overrides, out_path, err_path))
        except OSError:
            zyg["conn"] = None
            start_zygote()
            return False
        zpids[wid] = -1  # pid lands with the ("forked", ...) reply
        import time as _time

        spawn_ts[wid] = _time.monotonic()
        return True

    # OOM protection (ray: memory_monitor.h:52 + worker_killing_policy.h):
    # under memory pressure, kill ONE worker (retriable error head-side)
    # instead of letting the kernel OOM-killer take the whole daemon.
    from ray_tpu._private.memory_monitor import MemoryMonitor

    def _oom_workers():
        # list() snapshot: the monitor thread iterates while the main loop
        # spawns/reaps; mutating a dict mid-iteration raises and the beat
        # would be silently skipped exactly during post-kill churn.
        out = {
            wid: (p.pid, spawn_ts.get(wid, 0.0))
            for wid, p in list(children.items())
            if p.poll() is None
        }
        for wid, pid in list(zpids.items()):
            if pid > 0:
                out[wid] = (pid, spawn_ts.get(wid, 0.0))
        return out

    oom_killed: Dict[str, tuple] = {}

    def _oom_kill(wid: str, rss: int, used: int, limit: int) -> None:
        p = children.get(wid)
        zpid = zpids.get(wid)
        if p is None and not (zpid and zpid > 0):
            return
        # Record + tell the head FIRST so the crash is classified as OOM,
        # then SIGKILL — a graceful terminate could block on the very
        # allocation that caused the pressure.  The info also rides the
        # eventual worker_exited report (belt and braces: the worker's own
        # conn EOF races this message on a different socket).
        oom_killed[wid] = (rss, used, limit)
        try:
            with send_lock:
                conn.send(("worker_oom_killed", wid, rss, used, limit))
        except OSError:
            pass
        try:
            if p is not None:
                p.kill()
            else:
                os.kill(zpid, signal.SIGKILL)
        except OSError:
            pass

    refresh_ms = _config.get("memory_monitor_refresh_ms")
    mem_monitor = None
    if refresh_ms > 0:
        mem_monitor = MemoryMonitor(
            _oom_workers,
            _oom_kill,
            limit_bytes=_config.get("memory_limit_bytes"),
            threshold=_config.get("memory_usage_threshold"),
            interval_s=refresh_ms / 1000.0,
            policy=_config.get("oom_worker_killing_policy"),
        )
        mem_monitor.start()

    def shutdown(*_a):
        if mem_monitor is not None:
            mem_monitor.stop()
        if zyg["proc"] is not None:
            try:
                zyg["proc"].terminate()  # forked workers follow (pdeathsig)
            except OSError:
                pass
        for pid in zpids.values():
            if pid > 0:
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    pass
        for p in children.values():
            try:
                p.terminate()
            except OSError:
                pass
        for p in children.values():
            try:
                p.wait(timeout=2)
            except Exception:
                try:
                    p.kill()
                except OSError:
                    pass
        try:
            log_monitor.flush()  # last lines (incl. crash output) reach head
            log_monitor.stop()
        except Exception:
            pass
        obj_server.close()
        store.destroy()
        sys.exit(0)

    # Signal handlers only set a flag: shutdown() flushes logs through
    # send_lock, and a handler interrupting a frame that already holds it
    # (reap's send) would self-deadlock on the non-reentrant lock.
    stop_flag = {"stop": False}

    def _request_stop(*_a):
        stop_flag["stop"] = True

    signal.signal(signal.SIGTERM, _request_stop)
    signal.signal(signal.SIGINT, _request_stop)

    def reap() -> None:
        """Collect exited children (no zombies) and report them — the
        driver's reaper cannot see remote processes, so a worker that dies
        before connecting would otherwise hang its task forever."""
        for wid, p in list(children.items()):
            rc = p.poll()
            if rc is not None:
                children.pop(wid, None)
                spawn_ts.pop(wid, None)
                try:
                    with send_lock:
                        conn.send(("worker_exited", wid, rc, oom_killed.pop(wid, None)))
                except OSError:
                    pass

    # Liveness heartbeats (ray: gcs_health_check_manager.h:28-37 — the
    # reference PULLS health checks; a push on the existing conn gives the
    # head the same signal without another listener): a hung daemon or a
    # half-open TCP conn stops heartbeating and the head declares the node
    # dead on timeout instead of trusting EOF alone.
    import time as _time

    from multiprocessing.connection import wait as conn_wait

    start_zygote()
    hb_period = _config.get("health_check_period_ms") / 1000.0
    last_hb = 0.0
    push_period = max(_config.get("metrics_push_ms"), 0) / 1000.0
    last_push = 0.0

    pending_kills: set = set()  # kill_worker raced a fork in flight

    def _report_exited(wid: str, rc) -> None:
        zpids.pop(wid, None)
        spawn_ts.pop(wid, None)
        pending_kills.discard(wid)
        try:
            with send_lock:
                conn.send(("worker_exited", wid, rc, oom_killed.pop(wid, None)))
        except OSError:
            pass

    def drain_zygote() -> None:
        zc = zyg["conn"]
        while zc is not None:
            try:
                if not zc.poll(0):
                    return
                zmsg = zc.recv()
            except (EOFError, OSError):
                # Zygote died.  Its forked workers die with it (pdeathsig
                # chains zygote -> worker) and fork requests in flight are
                # lost — report every zygote worker exited so the head
                # reschedules instead of waiting on a reply that will
                # never come.
                zyg["conn"] = None  # respawned on the next spawn request
                for wid in list(zpids):
                    _report_exited(wid, -1)
                return
            if zmsg[0] == "forked":
                wid, pid = zmsg[1], zmsg[2]
                zpids[wid] = pid
                if wid in pending_kills:
                    # A kill_worker landed while the fork was in flight:
                    # apply it now instead of silently dropping it.
                    pending_kills.discard(wid)
                    try:
                        os.kill(pid, signal.SIGTERM)
                    except OSError:
                        pass
            elif zmsg[0] == "worker_exited":
                _report_exited(zmsg[1], zmsg[2])

    while True:
        if stop_flag["stop"]:
            shutdown()
            return
        now = _time.monotonic()
        if hb_period > 0 and now - last_hb >= hb_period:
            last_hb = now
            try:
                with send_lock:
                    conn.send(("heartbeat", node_id))
            except OSError:
                pass  # EOF path below handles reconnection
        if push_period > 0 and now - last_push >= push_period:
            # Telemetry push: the daemon's registry + wire counters plus
            # its store gauges, riding the same batch flush the heartbeat
            # does (droppable: a failed send just loses a tick).
            last_push = now
            snap = telemetry.snapshot_process(
                extra={
                    "node_live_workers": float(
                        len(children) + sum(1 for p in zpids.values() if p > 0)
                    ),
                }
            )
            try:
                with send_lock:
                    conn.send(("metrics_push", snap))
            except OSError:
                pass
        # Flush-before-blocking-wait: the heartbeat above plus any pending
        # log_lines / worker_exited / oom reports leave as one write.
        try:
            conn.flush()
        except OSError:
            pass  # EOF path below handles reconnection
        if conn.pending_frames():
            has_msg = True  # a decoded batch tail would never wake wait()
        else:
            try:
                waitset = [conn] + ([zyg["conn"]] if zyg["conn"] is not None else [])
                ready = conn_wait(waitset, timeout=0.5)
                has_msg = conn in ready
            except (EOFError, OSError):
                conn = reconnect()
                if conn is None:
                    shutdown()
                    return
                continue
        drain_zygote()
        reap()
        if not has_msg:
            continue
        msgs = []
        try:
            msgs.append(conn.recv())
            while len(msgs) < 64 and conn.poll(0):
                msgs.append(conn.recv())
            while conn.pending_frames():
                msgs.append(conn.recv())
        except (EOFError, OSError):
            # Head gone: reconnect in head-split mode, else this host's
            # pool dies with it.
            conn = reconnect()
            if conn is None:
                shutdown()
                return
            continue
        for msg in msgs:
            kind = msg[0]
            if kind == "spawn_worker":
                _, wid, renv = msg
                env = _build_worker_env(
                    wid, host, port, authkey_hex, session, renv, store_dir, node_id
                )
                if zyg["conn"] is None:
                    start_zygote()  # died/never started: next spawn forks
                if not zygote_fork(wid, env):
                    outf, errf = open_worker_logs(log_dir, wid)
                    if arena_fd is not None:
                        # Direct spawn inherits the arena fd (the zygote
                        # path receives it via SCM_RIGHTS instead).
                        env["RAY_TPU_ARENA_FD"] = str(arena_fd)
                    try:
                        children[wid] = subprocess.Popen(
                            [sys.executable, "-m", "ray_tpu._private.worker_proc"],
                            env=env,
                            close_fds=True,
                            pass_fds=(arena_fd,) if arena_fd is not None else (),
                            stdout=outf,
                            stderr=errf,
                        )
                        spawn_ts[wid] = _time.monotonic()
                    finally:
                        outf.close()
                        errf.close()
            elif kind == "kill_worker":
                p = children.get(msg[1])
                zpid = zpids.get(msg[1])
                if p is not None:
                    try:
                        p.terminate()
                    except OSError:
                        pass
                    # reap() collects and reports it next cycle
                elif zpid is not None and zpid > 0:
                    try:
                        os.kill(zpid, signal.SIGTERM)
                    except OSError:
                        pass
                    # the zygote reaps and reports it
                elif zpid == -1:
                    # Fork in flight: remember the kill for the ("forked",
                    # pid) reply instead of dropping it.
                    pending_kills.add(msg[1])
            elif kind == "delete_object":
                # Owner freed the object (refcount hit zero): drop this
                # node's copy (ray: the raylet's local object manager
                # eviction on ownership release).
                store.delete(msg[1])
            elif kind == "shutdown":
                shutdown()
                return


if __name__ == "__main__":
    main()
