"""Worker-to-worker direct task transport.

The reference's hot path for actor calls is peer-to-peer: the submitting
CoreWorker resolves the actor's worker address once and pushes every call
over a direct gRPC channel, with only ownership bookkeeping flowing to the
control plane asynchronously (ray:
src/ray/core_worker/transport/direct_actor_task_submitter.h:67,
direct_task_transport.h:75).  Rounds 1-3 of this build relayed every actor
call through the head process — one GIL-bound thread capping cluster-wide
call throughput and adding a double hop to every serve handle call.  This
module removes that hop:

  * every worker runs a `PeerServer` — an authkey-authenticated listener
    whose endpoint rides the worker's "ready" handshake to the head, which
    thereby becomes the address directory;
  * a caller resolves an actor once (`resolve_actor` head op, cached
    while the binding holds), then pushes calls as ("pcall", spec) frames
    on a persistent peer connection and receives ("pdone", task_id,
    results, err) frames on the same socket.  Restartable actors are
    direct too: on peer death the route enters "recovering" — new calls
    buffer in caller order, retry-eligible in-flight calls re-drive, and
    a background resolver follows the head's restart FSM to the new
    instance's endpoint (ray: direct_actor_task_submitter.h:67 +
    sequential_actor_submit_queue.h resubmit across restarts);
  * ordering: per-caller order is the TCP FIFO; when a caller previously
    relayed calls through the head (actor was still PENDING_CREATION), the
    switch to direct mode is fenced — the head flushes a marker through the
    actor worker's control connection and the caller only switches after
    the marker is acked, so a direct call can never overtake a relayed one
    (ray: sequential_actor_submit_queue.h gives the same guarantee with
    per-caller sequence numbers);
  * ownership: small results stay CALLER-owned — cached in the caller
    process, refcounted locally, and promoted to the head only if the ref
    escapes the caller (serialized into another task's args / a put / a
    result).  Large results seal into the callee's node store and the
    callee reports them to the head as an async oneway ("direct_seal"), so
    the transfer directory still sees every copy.  Failure semantics match
    the reference: max_restarts == 0 means in-flight calls on a dead peer
    connection fail with ActorDiedError, and a caller that dies with
    unpromoted results takes those objects with it (owner-death object
    loss, ray: reference_count.h owner semantics).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu._private import faults
from ray_tpu._private import lock_watchdog


class PeerServer:
    """In-worker listener accepting direct task pushes from peer workers.

    One recv thread per accepted connection demultiplexes ("pcall", spec)
    frames into the worker's executor queues (the same ordered FIFO /
    thread-pool routing head-pushed tasks use — per-caller order is the
    connection FIFO, cross-caller interleaving is unspecified, as in the
    reference's ActorSchedulingQueue fed by many gRPC channels).
    """

    def __init__(self, authkey: bytes, bind_host: str, advertise_host: str,
                 handler: Callable[[tuple, "PeerReply"], None]):
        from multiprocessing.connection import Listener

        self._handler = handler
        self.listener = Listener((bind_host, 0), backlog=128, authkey=authkey)
        self.endpoint: Tuple[str, int] = (advertise_host, self.listener.address[1])
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="raytpu-peer-accept"
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        from ray_tpu._private.netutil import set_nodelay

        while not self._shutdown:
            try:
                conn = self._accept_one()
            except (OSError, EOFError):
                if self._shutdown:
                    return
                continue
            if conn is None:
                continue
            set_nodelay(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="raytpu-peer-conn",
            ).start()

    def _accept_one(self):
        """One accept, hardened: Listener.accept runs the authkey HMAC
        challenge inline, so a stray connection (port scanner, wrong-key
        peer) raises AuthenticationError — which must not kill the accept
        loop (that would silently disable this worker's direct transport
        for the rest of its life)."""
        from ray_tpu._private.wire import ProtocolError, wrap

        try:
            return wrap(self.listener.accept())
        except (OSError, EOFError):
            raise
        except ProtocolError as e:
            # A version-skewed LEGITIMATE peer, not a stranger: silence
            # here would turn the loud r4 versioning feature into a
            # silent connect-retry loop on the direct path.
            import sys

            print(f"[ray_tpu] peer handshake rejected: {e}", file=sys.stderr,
                  flush=True)
            return None
        except Exception:
            return None  # bad handshake from a stranger: keep serving

    def _serve_conn(self, conn) -> None:
        from ray_tpu._private import wire as _wire

        reply = PeerReply(conn)
        while True:
            try:
                if not conn.pending_frames() and not conn.poll(0):
                    # Flush-before-blocking-wait: pdone frames from
                    # inline-executed tasks (worker_proc.peer_handler)
                    # coalesce while more pcalls are queued and go out
                    # the moment this conn would park.
                    _wire.flush_dirty()
                msg = conn.recv()
            except (OSError, EOFError):
                try:
                    conn.close()
                except OSError:
                    pass
                return
            try:
                self._handler(msg, reply)
            except Exception:
                import traceback

                traceback.print_exc()

    def close(self) -> None:
        self._shutdown = True
        try:
            self.listener.close()
        except OSError:
            pass


class PeerReply:
    """Send side of one accepted peer connection (executor threads share
    it).  Replies ride a BatchingConn: back-to-back pdone frames from a
    pipelined caller coalesce into one physical write (the executor's
    idle point and the linger sweep flush them — worker_proc main loop).
    send_lock is a dedicated wire-serialization lock — it exists only to
    keep concurrent reply frames from interleaving on the shared conn,
    never wraps anything but the send, and is named for the concurrency
    lint's serialization-idiom exemption."""

    __slots__ = ("conn", "send_lock")

    def __init__(self, conn):
        from ray_tpu._private import wire as _wire

        self.conn = _wire.batching(conn)
        self.send_lock = lock_watchdog.make_lock("PeerReply.send_lock")

    def send(self, msg: tuple) -> None:
        try:
            with self.send_lock:
                self.conn.send(msg)
        except (OSError, ValueError):
            pass  # caller vanished; its results are owner-lost


class PeerConn:
    """Caller-side persistent connection to one peer worker.

    Owns a recv thread routing ("pdone", ...) frames to the transport's
    completion callback.  On EOF the death callback fails (or, for
    restartable actors, re-drives) every in-flight call.
    """

    def __init__(self, endpoint: Tuple[str, int], authkey: bytes,
                 on_done: Callable[[tuple], None], on_death: Callable[["PeerConn"], None]):
        from ray_tpu._private.object_plane import _connect_with_deadline
        from ray_tpu._private import config as _config

        self.endpoint = tuple(endpoint)
        if faults.ENABLED:
            # error -> OSError out of the constructor: the route falls back
            # exactly as for a real connect failure (relay / retry).
            faults.point("peer.connect", key=f"{endpoint[0]}:{endpoint[1]}")
        from ray_tpu._private import wire as _wire

        # Batching sender: a client's tight submit loop coalesces its
        # pcall pushes into one write per flush wave (the caller's
        # blocking points — get_local — flush explicitly; the linger
        # sweep bounds fire-and-forget latency).  A flush failure marks
        # the conn broken, so send() below still reports death at the
        # call site like the unbatched conn did.
        self.conn = _wire.batching(
            _connect_with_deadline(
                self.endpoint, authkey, _config.get("object_transfer_timeout_s")
            )
        )
        self.send_lock = lock_watchdog.make_lock("PeerConn.send_lock")
        self.dead = False
        self._on_done = on_done
        self._on_death = on_death
        self._thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="raytpu-peer-client"
        )
        self._thread.start()

    def send(self, msg: tuple) -> bool:
        if self.dead:
            return False
        try:
            if faults.ENABLED and faults.point(
                "peer.send", key=msg[0] if msg else None
            ) == "drop":
                return True  # lost on the wire: the caller believes it sent
            with self.send_lock:
                self.conn.send(msg)
            return True
        except (OSError, ValueError):
            return False

    def flush(self) -> None:
        """Push any pending pcall batch now (cancel paths, re-drives)."""
        try:
            self.conn.flush()
        except (OSError, ValueError):
            pass  # the recv loop's EOF owns the death handling

    def _recv_loop(self) -> None:
        while True:
            try:
                msg = self.conn.recv()
            except (OSError, EOFError):
                self.dead = True
                try:
                    self.conn.close()
                except OSError:
                    pass
                self._on_death(self)
                return
            try:
                self._on_done(msg)
            except Exception:
                import traceback

                traceback.print_exc()

    def close(self) -> None:
        self.dead = True
        try:
            self.conn.close()
        except OSError:
            pass


class DirectResult:
    """Caller-local record of one direct-call return object.

    States: pending (call in flight) → value/error.  `escaped` marks a ref
    that was serialized out of this process while pending — promotion to
    the head happens the moment the value lands.
    """

    __slots__ = ("event", "kind", "data", "contained", "escaped", "promoted")

    def __init__(self):
        self.event = threading.Event()
        self.kind: Optional[str] = None  # inline | shm | error
        self.data: Any = None
        self.contained: list = []
        self.escaped = False
        self.promoted = False


class ActorRoute:
    """Caller-side routing state for one direct actor (ray:
    direct_actor_task_submitter.h:67 keeps the same per-actor client
    queue).  Non-restartable actors: state is "direct" until the peer
    conn dies, then the route is dropped (in-flight fails ActorDiedError).
    Restartable actors: conn death flips the route to "recovering" — new
    calls buffer IN ORDER caller-side, retry-eligible in-flight calls are
    prepended to that buffer, and a background resolver polls the head's
    restart FSM until the new instance's endpoint arrives (then re-drives
    the buffer) or the actor is declared dead (then fails it)."""

    __slots__ = ("state", "conn", "restartable", "buffered", "recover_started")

    def __init__(self, conn: "PeerConn", restartable: bool):
        self.state = "direct"  # "direct" | "recovering"
        self.conn: Optional[PeerConn] = conn
        self.restartable = restartable
        self.buffered: list = []  # specs queued while recovering
        self.recover_started = False


class Lease:
    """One head-granted worker lease (ray: direct_task_transport.h:75 —
    lease pooling keyed by SchedulingKey, reused across same-shape tasks)."""

    __slots__ = ("lease_id", "worker_id", "conn", "inflight", "last_used")

    def __init__(self, lease_id: str, worker_id: str, conn: PeerConn):
        import time as _time

        self.lease_id = lease_id
        self.worker_id = worker_id
        self.conn = conn
        self.inflight = 0
        # Stamped at creation: a zero would read as idle-since-forever and
        # let the maintenance tick return a just-granted lease.
        self.last_used = _time.monotonic()


# How many unacked tasks one lease pipelines before another worker is
# leased, how many workers one key may hold, and how long an idle lease is
# kept before being returned to the head's pool.  Defaults ADAPT to the
# host's parallelism: on a many-core machine, spreading a burst across
# workers buys real concurrency (the reference pipelines 4 deep and fans
# out); on a 1-2 vCPU host the same fan-out only multiplies processes
# fighting for the one core — pipelining DEEP onto few executors measured
# ~25% faster multi-client throughput with a third the context-switch
# churn.  RAY_TPU_LEASE_PIPELINE_DEPTH / RAY_TPU_LEASE_MAX_PER_KEY
# override (0 = auto).
def _lease_tuning():
    import os as _os

    from ray_tpu._private import config as _config

    cpus = _os.cpu_count() or 1
    depth = _config.get("lease_pipeline_depth")
    per_key = _config.get("lease_max_per_key")
    if depth <= 0:
        depth = max(4, 64 // cpus)
    if per_key <= 0:
        per_key = max(1, min(8, cpus))
    return depth, per_key


_LEASE_PIPELINE, _LEASE_MAX_PER_KEY = _lease_tuning()
_LEASE_IDLE_RETURN_S = 2.0


class DirectTransport:
    """Caller-side state machine for direct calls (one per worker).

    Actor calls: resolution cache is sticky — an ActorRoute and "head"
    (relay) are both terminal per actor, since mixing transports per
    (caller, actor) would break per-caller call order.  A restartable
    actor's route survives instance deaths by recovering in place.

    Plain tasks: the head grants reusable worker LEASES per scheduling key
    (resource shape); tasks push directly to leased workers, so per-task
    head traffic is O(1 lease per key-burst), not O(1 request per task)
    (ray: direct_task_transport.h:75, local_task_manager.h:58 — our
    leases still reserve through the head's scheduler, which is what makes
    spillback and backpressure fall out: a full cluster denies the lease
    and the task takes the queued head path).
    """

    def __init__(self, wr):
        self.wr = wr  # WorkerRuntime
        self.lock = lock_watchdog.make_lock("DirectTransport.lock")
        self.routes: Dict[str, Any] = {}  # actor_id -> ActorRoute | "head"
        self.conns: Dict[Tuple[str, int], PeerConn] = {}
        self.used_head_path: set = set()  # actor_ids relayed at least once
        # oid -> DirectResult for every in-flight or cached direct return.
        self.results: Dict[str, DirectResult] = {}
        self.counts: Dict[str, int] = {}  # local refcounts for owned oids
        # task_id -> (actor_id | None, spec, conn[, lease]) — actor calls
        # carry the actor id, leased plain tasks carry None + their lease.
        self.inflight: Dict[str, tuple] = {}
        self.calls_sent = 0  # diagnostics
        self.leases: Dict[Any, list] = {}  # key -> [Lease]
        self.lease_backoff: Dict[Any, float] = {}  # key -> retry-not-before
        self._maint_started = False

    # -- routing -------------------------------------------------------------

    def _resolve(self, actor_id: str) -> bool:
        """Establish a route for an unresolved actor; False = relay."""
        need_fence = actor_id in self.used_head_path
        try:
            reply = self.wr.request(
                "resolve_actor", (actor_id, need_fence), timeout=30.0
            )
            status, endpoint = reply[0], reply[2]
            restartable = bool(reply[3]) if len(reply) > 3 else False
        except queue.Empty:
            # Head slow: relay this call and retry resolve next time.  The
            # relay MUST be recorded — a later unfenced switch to direct
            # mode could overtake it (per-caller ordering violation).
            with self.lock:
                self.used_head_path.add(actor_id)
            return False
        except Exception:
            status, endpoint, restartable = "head", None, False
        if status != "direct":
            if status in ("ineligible", "dead"):
                with self.lock:
                    self.routes[actor_id] = "head"
            # "pending": stay unresolved; relay and re-resolve on a later call
            with self.lock:
                self.used_head_path.add(actor_id)
            return False
        conn = self._conn_to(tuple(endpoint))
        if conn is None:
            with self.lock:
                self.routes[actor_id] = "head"
                self.used_head_path.add(actor_id)
            return False
        with self.lock:
            if not isinstance(self.routes.get(actor_id), ActorRoute):
                self.routes[actor_id] = ActorRoute(conn, restartable)
        return True

    def _conn_to(self, endpoint: Tuple[str, int]) -> Optional[PeerConn]:
        with self.lock:
            conn = self.conns.get(endpoint)
            if conn is not None and not conn.dead:
                return conn
        try:
            conn = PeerConn(endpoint, self.wr.authkey, self._on_done, self._on_conn_death)
        except (OSError, EOFError):
            return None
        with self.lock:
            old = self.conns.get(endpoint)
            if old is not None and not old.dead:
                conn.close()
                return old
            self.conns[endpoint] = conn
        return conn

    # -- submission ----------------------------------------------------------

    def _register(self, spec, conn, lease=None) -> list:
        """Caller bookkeeping shared by actor calls and leased tasks."""
        return_ids = spec.return_ids()
        # Borrow every arg ref for the call's lifetime BEFORE the push: the
        # add must precede (same head conn, FIFO) any release the caller's
        # own ref GC emits after this call returns.
        for c in spec.contained_refs:
            self.wr.borrow_ref(c)
        with self.lock:
            for oid in return_ids:
                self.results[oid] = DirectResult()
                # Pre-count the ObjectRef the caller is ABOUT to construct
                # (created with _count=False): if the callee replies before
                # that construction, a zero count would release the entry
                # under the caller's feet.
                self.counts[oid] = 1
            self.inflight[spec.task_id] = (spec.actor_id, spec, conn, lease)
        if lease is not None and self.wr.task_event_sink is not None:
            # Caller-side RUNNING report (batched off the latency path) so
            # lease-dispatched work shows in the head's task table (ray:
            # task events flow through TaskEventBuffer the same way).
            import time as _time

            self.wr.task_event_sink(
                {
                    "task_id": spec.task_id,
                    "name": spec.name,
                    "state": "RUNNING",
                    "worker_id": lease.worker_id,
                    "actor_id": None,
                    "parent_task_id": spec.parent_task_id,
                    "attempt": spec.attempt,
                    "start_time": _time.time(),
                    "direct": True,
                }
            )
        return return_ids

    def submit(self, spec) -> Optional[list]:
        """Try the direct actor path; returns return_ids or None (relay).

        Restartable actors ride the direct path too: a call landing while
        the route is recovering buffers caller-side (never relays — a
        relay could overtake the re-driven buffer, breaking per-caller
        order) and is flushed onto the restarted instance's conn."""
        aid = spec.actor_id
        with self.lock:
            r = self.routes.get(aid)
        if r == "head":
            return None
        if not isinstance(r, ActorRoute):
            if not self._resolve(aid):
                return None
        reg = self._register_actor(spec)
        if reg is None:
            return None  # route vanished between resolve and register: relay
        return_ids, conn = reg
        if conn is None:
            return return_ids  # buffered behind a restart in progress
        if not conn.send(("pcall", spec)):
            # Connection died between resolve and push: recover (restartable)
            # or fail like an actor death (no silent re-relay — the relay
            # could double-execute).
            self._fail_inflight_on(conn)
            return return_ids
        self.calls_sent += 1
        return return_ids

    def _register_actor(self, spec):
        """Caller bookkeeping for one direct actor call.  Returns
        (return_ids, conn) — conn None when buffered behind a recovery —
        or None when the route vanished (caller relays instead)."""
        # Borrow every arg ref BEFORE registering/pushing: the add must
        # precede (same head conn, FIFO) any release the caller's own ref
        # GC emits after this call returns.
        for c in spec.contained_refs:
            self.wr.borrow_ref(c)
        return_ids = spec.return_ids()
        aid = spec.actor_id
        with self.lock:
            r = self.routes.get(aid)
            if isinstance(r, ActorRoute):
                for oid in return_ids:
                    self.results[oid] = DirectResult()
                    # Pre-count the ObjectRef the caller is ABOUT to
                    # construct (created with _count=False): if the callee
                    # replies before that construction, a zero count would
                    # release the entry under the caller's feet.
                    self.counts[oid] = 1
                dead_conn = r.conn is None or r.conn.dead
                if (r.state == "recovering" or dead_conn) and r.restartable:
                    # The death callback (or an already-running recovery)
                    # owns the flush; per-caller order = buffer order.
                    self.inflight[spec.task_id] = (aid, spec, None, None)
                    r.buffered.append(spec)
                    return return_ids, None
                # Non-restartable dead conn: bind to it anyway — the send
                # fails and the fail path lands ActorDiedError.
                self.inflight[spec.task_id] = (aid, spec, r.conn, None)
                return return_ids, r.conn
        # Route vanished (non-restartable death raced us): balance borrows.
        for c in spec.contained_refs:
            self.wr.unborrow_ref(c)
        return None

    # -- leased plain tasks --------------------------------------------------

    @staticmethod
    def _plain_eligible(spec) -> bool:
        return (
            spec.actor_id is None
            and not spec.is_actor_creation
            and spec.scheduling_strategy in (None, "DEFAULT")
            and spec.placement_group_id is None
            and not spec.runtime_env
        )

    @staticmethod
    def _lease_key(spec):
        return frozenset(spec.resources.items())

    def submit_plain(self, spec) -> Optional[list]:
        """Push a plain task to a head-leased worker; None = relay.

        Tradeoffs vs the head path, by design: arg-locality node scoring
        does not apply — the win is zero per-task head requests.  Crash
        retries run caller-side against a fresh lease (same at-least-once
        semantics); sealed results are lineage-reconstructable via
        direct_lineage."""
        if not self._plain_eligible(spec):
            return None
        # Deadlock guard: the head path dep-gates BEFORE occupying a
        # worker; a direct push occupies the leased worker through arg
        # resolution.  A task whose dep is still BEING PRODUCED could
        # therefore park leased workers while the producer starves for the
        # very resources those leases hold.  Only push when every dep is
        # provably materialized: caller-owned and landed, sealed in this
        # node's store, or seen by this process (known_materialized) — a
        # produced-but-remote dep is safe, the executor stages the bytes
        # over the transfer plane at arg resolution (ray:
        # dependency_manager.h:51 pulls deps node-locally the same way).
        for d in spec.deps:
            r = self.ready_local(d)
            if r is False:
                return None  # ours, still in flight
            if r is None and not self.wr.known_materialized(d):
                return None  # not provably produced: let the head gate it
        lease = self._acquire_lease(self._lease_key(spec), spec)
        if lease is None:
            return None
        return_ids = self._register(spec, lease.conn, lease)
        if not lease.conn.send(("pcall", spec)):
            self._fail_inflight_on(lease.conn)
            return return_ids
        self.calls_sent += 1
        self._ensure_maintenance()
        return return_ids

    def _acquire_lease(self, key, spec, ignore_backoff: bool = False):
        """Select-or-grant a lease and bump its inflight count in ONE lock
        hold — selection and increment in separate holds would race the
        maintenance tick, which returns idle leases to the head (a task
        could land on a worker the head already re-pooled).

        Policy: take a lease with pipeline headroom; at the per-key cap (or
        when a grant is denied — cluster full) pipeline DEEP onto the least
        loaded instead of splitting the burst with the head queue, which
        convoys: the head backlog would wait on the very CPUs our leases
        hold.  Relay (None) only when the key holds no lease at all."""
        import time as _time

        grant_allowed = True
        with self.lock:
            pool = [l for l in self.leases.get(key, []) if not l.conn.dead]
            self.leases[key] = pool
            if pool:
                best = min(pool, key=lambda l: l.inflight)
                if best.inflight < _LEASE_PIPELINE or len(pool) >= _LEASE_MAX_PER_KEY:
                    best.inflight += 1
                    best.last_used = _time.monotonic()
                    return best
            if not ignore_backoff and self.lease_backoff.get(key, 0) > _time.monotonic():
                grant_allowed = False
        if grant_allowed:
            granted = self._grant_lease(key, spec)
            if granted is not None:
                return granted  # registered + incremented by _grant_lease
        with self.lock:
            pool = [l for l in self.leases.get(key, []) if not l.conn.dead]
            if not pool:
                return None
            best = min(pool, key=lambda l: l.inflight)
            best.inflight += 1
            best.last_used = _time.monotonic()
            return best

    def _grant_lease(self, key, spec) -> Optional[Lease]:
        """Request one worker lease from the head; on success the lease is
        registered AND pre-incremented for the caller (atomic with its
        insertion, so the maintenance tick can never doom it first)."""
        import time as _time

        try:
            reply = self.wr.request(
                "lease_worker", (dict(spec.resources),), timeout=15.0
            )
        except Exception:
            reply = ("busy",)
        if not (isinstance(reply, tuple) and reply and reply[0] == "ok"):
            with self.lock:
                self.lease_backoff[key] = _time.monotonic() + 0.25
            return None
        _, lease_id, worker_id, endpoint = reply
        conn = self._conn_to(tuple(endpoint))
        if conn is None:
            self.wr.oneway(("lease_return", lease_id))
            with self.lock:
                self.lease_backoff[key] = _time.monotonic() + 0.25
            return None
        lease = Lease(lease_id, worker_id, conn)
        with self.lock:
            lease.inflight += 1
            self.leases.setdefault(key, []).append(lease)
        return lease

    def _ensure_maintenance(self) -> None:
        with self.lock:
            if self._maint_started:
                return
            self._maint_started = True
        t = threading.Thread(
            target=self._maintenance_loop, daemon=True, name="raytpu-leases"
        )
        t.start()

    def _maintenance_loop(self) -> None:
        """Return leases idle past the keep-alive so the head can re-pool
        the workers (ray: lease reuse with idle release)."""
        import time as _time

        while True:
            _time.sleep(1.0)
            now = _time.monotonic()
            doomed = []
            with self.lock:
                for key, pool in list(self.leases.items()):
                    keep = []
                    for l in pool:
                        if l.conn.dead or (
                            l.inflight == 0
                            and now - l.last_used > _LEASE_IDLE_RETURN_S
                        ):
                            doomed.append(l)
                        else:
                            keep.append(l)
                    if keep:
                        self.leases[key] = keep
                    else:
                        self.leases.pop(key, None)
            for l in doomed:
                self.wr.oneway(("lease_return", l.lease_id))

    def _resend(self, spec) -> bool:
        """Re-push a crashed/retried task on a fresh lease, keeping the
        existing (still-pending) result registrations."""
        lease = self._acquire_lease(
            self._lease_key(spec), spec, ignore_backoff=True
        )
        if lease is None:
            return False
        with self.lock:
            self.inflight[spec.task_id] = (None, spec, lease.conn, lease)
        if not lease.conn.send(("pcall", spec)):
            with self.lock:
                self.inflight.pop(spec.task_id, None)
                lease.inflight -= 1
            return False
        return True

    # -- completion ----------------------------------------------------------

    def _on_done(self, msg: tuple) -> None:
        if msg[0] != "pdone":
            return
        _, task_id, results, err_blob = msg
        with self.lock:
            entry = self.inflight.pop(task_id, None)
        if entry is None:
            return
        _aid, spec, _conn, lease = entry
        if lease is not None:
            with self.lock:
                lease.inflight -= 1
        err = None
        if err_blob is not None:
            import cloudpickle

            try:
                err = cloudpickle.loads(err_blob)
            except BaseException as e:  # noqa: BLE001 — error class not
                # importable here (e.g. callee-only runtime_env module):
                # land a descriptive fallback rather than dropping the
                # completion (which would hang the caller's get forever).
                err = RuntimeError(
                    f"direct call {task_id} failed with an error that could "
                    f"not be deserialized in the caller: {e!r}"
                )
        from ray_tpu.exceptions import TaskCancelledError

        if (
            err is not None
            and spec.retry_exceptions
            and spec.attempt < spec.max_retries
            # A cancel is a user decision, not a failure: retrying it
            # would silently undo ray_tpu.cancel.
            and not isinstance(err, TaskCancelledError)
        ):
            spec.attempt += 1
            if lease is not None:
                if self._resend(spec):
                    return  # retried: pending results land on a later pdone
            elif _aid is not None and self._resend_actor(_aid, spec):
                return
        for oid in spec.return_ids():
            value = None
            if err is None:
                for item in results:
                    if item[0] == oid:
                        value = item
                        break
            self._land(
                oid,
                err if err is not None else (
                    None if value is not None else RuntimeError(
                        f"direct call {task_id} returned no value for {oid}"
                    )
                ),
                value,
            )
        # Release arg borrows (after results are registered: FIFO with the
        # borrow adds on the same head conn).
        for c in spec.contained_refs:
            self.wr.unborrow_ref(c)

    def _land(self, oid: str, err, item) -> None:
        """Record one completed return object.  Promotion, the completion
        event, and release bookkeeping are linearized under the transport
        lock (the head-conn sends inside are leaf operations), so an
        escape racing the completion promotes exactly once and a ref drop
        racing it releases exactly once."""
        with self.lock:
            dr = self.results.get(oid)
            if dr is None or dr.event.is_set():
                return
            if err is not None:
                dr.kind, dr.data = "error", err
            else:
                _oid, kind, data, contained = item
                dr.kind, dr.data, dr.contained = kind, data, list(contained)
            if dr.escaped and self._claim_promotion(dr):
                self._send_promotion(oid, dr)
            dr.event.set()
            self._release_locked(oid)

    def _fail_inflight_on(self, conn: PeerConn) -> None:
        from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError

        recover_aids = []
        with self.lock:
            doomed = [
                (tid, e) for tid, e in self.inflight.items() if e[2] is conn
            ]
            for tid, _ in doomed:
                self.inflight.pop(tid, None)
            for aid, r in list(self.routes.items()):
                if isinstance(r, ActorRoute) and r.conn is conn:
                    if r.restartable:
                        # Restart FSM owns this death: buffer instead of
                        # fail (ray: direct_actor_task_submitter.h:67
                        # resubmits across restarts).
                        r.state = "recovering"
                        r.conn = None
                        if not r.recover_started:
                            r.recover_started = True
                            recover_aids.append(aid)
                    else:
                        self.routes.pop(aid, None)
            # Retry-eligible actor calls on a recovering route are
            # PREPENDED to the route's buffer inside this same lock hold:
            # a submit racing the death may already have buffered newer
            # calls, and the in-flight ones must re-drive first
            # (per-caller order).
            resubmits: Dict[str, list] = {}
            kept: set = set()
            for tid, (aid, spec, _c, lease) in doomed:
                if lease is not None or aid is None:
                    continue
                r = self.routes.get(aid)
                if (
                    isinstance(r, ActorRoute)
                    and r.state == "recovering"
                    and spec.attempt < spec.max_retries
                ):
                    spec.attempt += 1
                    self.inflight[tid] = (aid, spec, None, None)
                    resubmits.setdefault(aid, []).append(spec)
                    kept.add(tid)
            for aid, specs in resubmits.items():
                r = self.routes.get(aid)
                r.buffered[:0] = specs
        for tid, (aid, spec, _c, lease) in doomed:
            if tid in kept:
                continue
            if lease is not None:
                with self.lock:
                    lease.inflight -= 1
                # Leased plain task: crash retries run caller-side against
                # a fresh lease (ray: owner-side TaskManager resubmission).
                if spec.attempt < spec.max_retries:
                    spec.attempt += 1
                    if self._resend(spec):
                        continue
                err: Exception = WorkerCrashedError(
                    f"worker running task {spec.name} died unexpectedly"
                )
            else:
                err = ActorDiedError(aid)
            for oid in spec.return_ids():
                self._land(oid, err, None)
            for c in spec.contained_refs:
                self.wr.unborrow_ref(c)
        for aid in recover_aids:
            threading.Thread(
                target=self._recover_actor, args=(aid,), daemon=True,
                name="raytpu-actor-recover",
            ).start()

    def _recover_actor(self, aid: str) -> None:
        """Poll the head's restart FSM until the actor is ALIVE again (then
        re-drive the route's buffer onto the new endpoint, in order) or
        DEAD (then fail the buffer).  Never relays: per-caller order across
        the restart is preserved entirely caller-side (ray:
        sequential_actor_submit_queue.h rebuilds its queue the same way)."""
        import time as _time

        backoff = 0.05
        ineligible_deadline = None
        while True:
            try:
                reply = self.wr.request("resolve_actor", (aid, False), timeout=30.0)
                status = reply[0]
            except queue.Empty:
                status = "pending"
            except Exception:
                # Head unreachable (restarting?): keep polling — the worker
                # process itself dies if the head never comes back, and
                # declaring the ACTOR dead on a HEAD hiccup would be wrong.
                status = "pending"
            if status == "ineligible":
                # ALIVE but momentarily unroutable (worker conn/peer
                # endpoint gap during the restart hand-off): retry like
                # "pending", but bounded — a worker whose peer listener
                # failed to bind stays ineligible forever.
                if ineligible_deadline is None:
                    ineligible_deadline = _time.monotonic() + 60.0
                if _time.monotonic() < ineligible_deadline:
                    status = "pending"
            else:
                ineligible_deadline = None
            if status == "pending":
                _time.sleep(backoff)
                backoff = min(backoff * 2, 0.5)
                continue
            if status == "direct":
                conn = self._conn_to(tuple(reply[2]))
                if conn is None:
                    _time.sleep(backoff)
                    backoff = min(backoff * 2, 0.5)
                    continue
                # Flush + route flip under ONE lock hold: flipping to
                # "direct" before the buffer drains would let a racing
                # submit() push a newer call ahead of the re-driven backlog
                # (per-caller order violation).  conn.send is a leaf (it
                # takes only the frame lock), so sending under the
                # transport lock cannot deadlock.
                send_failed = False
                with self.lock:
                    r = self.routes.get(aid)
                    if not isinstance(r, ActorRoute):
                        return
                    to_send = r.buffered
                    r.buffered = []
                    for spec in to_send:
                        self.inflight[spec.task_id] = (aid, spec, conn, None)
                    sent = 0
                    for spec in to_send:
                        try:
                            if faults.ENABLED:
                                faults.point("peer.redrive", key=spec.task_id)
                            ok = conn.send(("pcall", spec))
                        except faults.InjectedFault:
                            ok = False
                        if not ok:
                            send_failed = True
                            break
                        sent += 1
                        self.calls_sent += 1
                    if send_failed:
                        # The flush broke before to_send[sent:] hit the
                        # socket: those calls provably never ran.  Un-bind
                        # them from the conn and re-buffer IN ORDER,
                        # uncharged — the death path below charges
                        # spec.attempt only for the sent prefix it
                        # re-drives (same never-ran un-charge
                        # _resend_actor applies).
                        for spec in to_send[sent:]:
                            self.inflight[spec.task_id] = (aid, spec, None, None)
                        r.buffered[:0] = to_send[sent:]
                    r.conn = conn
                    r.state = "direct"
                    r.recover_started = False
                # The re-driven backlog must not sit in a batch (duck-typed:
                # unit tests drive this path with plain mock conns).
                flush = getattr(conn, "flush", None)
                if flush is not None:
                    flush()
                if send_failed:
                    self._fail_inflight_on(conn)  # re-enters recovery
                return
            # dead / ineligible: the actor is gone for good
            with self.lock:
                r = self.routes.get(aid)
                if not isinstance(r, ActorRoute):
                    return
                self.routes[aid] = "head"  # future calls relay (head errors them)
                buffered = r.buffered
                for spec in buffered:
                    self.inflight.pop(spec.task_id, None)
            from ray_tpu.exceptions import ActorDiedError

            err = ActorDiedError(aid)
            for spec in buffered:
                for oid in spec.return_ids():
                    self._land(oid, err, None)
                for c in spec.contained_refs:
                    self.wr.unborrow_ref(c)
            return

    def _resend_actor(self, aid: str, spec) -> bool:
        """Re-push a retry-eligible failed actor call on the actor's
        current route (same instance — app-exception retry), keeping the
        existing (still-pending) result registrations."""
        with self.lock:
            r = self.routes.get(aid)
            if not isinstance(r, ActorRoute):
                return False
            if r.state == "recovering" or r.conn is None:
                self.inflight[spec.task_id] = (aid, spec, None, None)
                r.buffered.append(spec)
                return True
            conn = r.conn
            self.inflight[spec.task_id] = (aid, spec, conn, None)
        if conn.send(("pcall", spec)):
            return True
        # This retry never ran: un-charge it so the death path's own
        # re-charge doesn't bill two attempts for one observable failure.
        spec.attempt -= 1
        self._fail_inflight_on(conn)  # owns the outcome (recover or fail)
        return True

    def _on_conn_death(self, conn: PeerConn) -> None:
        self._fail_inflight_on(conn)

    def cancel(self, oid: str) -> bool:
        """Best-effort cancel of an in-flight direct call by return-oid.

        Matches the reference's actor-task cancel semantics (queued calls
        are dropped, a RUNNING method is not interrupted — force-kill of an
        actor rides ray_tpu.kill, not cancel).  Returns True when the oid
        belongs to a direct call this transport is tracking (cancelled or
        already finished — either way the head has nothing to do)."""
        doomed = None
        with self.lock:
            target = None
            for tid, entry in self.inflight.items():
                if oid in entry[1].return_ids():
                    target = (tid, entry)
                    break
            if target is None:
                return oid in self.results  # finished (or never direct)
            tid, (aid, spec, conn, _lease) = target
            if conn is None:
                # Buffered behind an actor recovery: queued-drop semantics
                # apply caller-side — the call never reached any executor.
                self.inflight.pop(tid, None)
                r = self.routes.get(aid)
                if isinstance(r, ActorRoute) and spec in r.buffered:
                    r.buffered.remove(spec)
                doomed = spec
        if doomed is not None:
            from ray_tpu.exceptions import TaskCancelledError

            err = TaskCancelledError(doomed.name)
            for o in doomed.return_ids():
                self._land(o, err, None)
            for c in doomed.contained_refs:
                self.wr.unborrow_ref(c)
            return True
        conn.send(("pcancel", tid))
        # Urgency frame: waiting in a batch lets the doomed call start
        # (duck-typed — tests drive this path with plain mock conns).
        flush = getattr(conn, "flush", None)
        if flush is not None:
            flush()
        return True

    # -- ownership -----------------------------------------------------------

    def owns(self, oid: str) -> bool:
        with self.lock:
            return oid in self.results

    def addref(self, oid: str) -> bool:
        with self.lock:
            if oid not in self.counts:
                return False
            self.counts[oid] += 1
            return True

    def decref(self, oid: str) -> bool:
        with self.lock:
            c = self.counts.get(oid)
            if c is None:
                return False
            if c > 1:
                self.counts[oid] = c - 1
                return True
            self.counts[oid] = 0
            self._release_locked(oid)
        return True

    def _release_locked(self, oid: str) -> None:
        """Caller holds self.lock.  Drop the cache entry once the value has
        landed AND the local count is zero.  Promoted/shm objects
        additionally release the head-side reference that
        direct_seal/promotion registered; inline entries release the
        callee-held borrows on any refs contained in the value."""
        dr = self.results.get(oid)
        if dr is None or not dr.event.is_set() or self.counts.get(oid, 0) > 0:
            return
        self.results.pop(oid, None)
        self.counts.pop(oid, None)
        if dr.kind == "shm" or dr.promoted:
            self.wr.oneway(("refop", "del", oid))
        if dr.kind == "inline":
            for c in dr.contained:
                self.wr.oneway(("refop", "del", c))

    # -- escape / promotion ----------------------------------------------------

    def mark_escaped(self, oid: str) -> None:
        """Called at serialize time when an owned ref leaves this process:
        the head must learn the object so other processes can resolve it.
        The escaped/promoted flags and the completion event are read and
        written under ONE lock on both the escape and completion sides, so
        exactly one of them performs the promotion."""
        with self.lock:
            dr = self.results.get(oid)
            if dr is None:
                return
            if not dr.event.is_set():
                dr.escaped = True  # _land promotes when the value lands
                return
            if self._claim_promotion(dr):
                # Send under the lock: a concurrent ref drop's release (also
                # lock-serialized) must see promoted=True only AFTER the
                # promote oneway is on the wire, or its balancing refop del
                # would overtake the add.
                self._send_promotion(oid, dr)

    def _claim_promotion(self, dr: DirectResult) -> bool:
        # caller holds self.lock
        if dr.promoted:
            return False
        dr.promoted = True
        return True

    def replay_promotions(self) -> None:
        """After a head restart: re-send every landed, already-promoted
        caller-owned result — the old head's memory store died with it,
        and borrowers elsewhere still hold the refs (ray: workers
        re-registering state with a restarted GCS)."""
        with self.lock:
            for oid, dr in list(self.results.items()):
                if dr.promoted and dr.event.is_set():
                    self._send_promotion(oid, dr)

    def announce_routes(self) -> None:
        """After a head restart: re-announce every live direct actor route
        this caller holds (reconciliation handshake, caller leg).  The
        head cross-checks the entries against its rebuilt actor table —
        a route it cannot account for means a durability gap and is
        surfaced loudly head-side."""
        with self.lock:
            entries = [
                (aid, getattr(r.conn, "endpoint", None))
                for aid, r in self.routes.items()
                if isinstance(r, ActorRoute)
                and r.conn is not None
                and not r.conn.dead
            ]
        if entries:
            self.wr.oneway(("actor_announce", entries))

    def _send_promotion(self, oid: str, dr: DirectResult) -> None:
        """Upload an owned object's bytes (inline) or error to the head.
        shm results were already registered by the callee's direct_seal —
        the claimed promotion is then a no-op (but keeps the release
        bookkeeping symmetric: a promoted entry always sends a refop del)."""
        if dr.kind == "inline":
            self.wr.oneway(("promote", oid, dr.data, dr.contained))
        elif dr.kind == "error":
            import cloudpickle

            self.wr.oneway(("promote_error", oid, cloudpickle.dumps(dr.data)))

    # -- reads ----------------------------------------------------------------

    def get_local(self, oid: str, timeout: Optional[float]):
        """Resolve an owned oid to (found, value_or_raiser).  Blocks until
        the in-flight call lands or the timeout lapses."""
        with self.lock:
            dr = self.results.get(oid)
        if dr is None:
            return False, None
        if not dr.event.is_set():
            # Flush-before-blocking-wait: the pcall's companion oneways
            # (borrow refops) and anything else pending must be on the
            # wire before this thread parks on the result.
            from ray_tpu._private import wire as _wire

            _wire.flush_dirty()
        if not dr.event.wait(timeout):
            from ray_tpu.exceptions import GetTimeoutError

            raise GetTimeoutError(f"get({oid}) timed out")
        if dr.kind == "error":
            raise dr.data
        if dr.kind == "inline":
            from ray_tpu._private import serialization as ser

            payload, bufs = ser.unpack(memoryview(dr.data))
            return True, ser.deserialize(payload, bufs, self.wr.ref_factory)
        # shm: sealed in the callee's node store; if that's our node the
        # store read hits, else fall to the owner/transfer path.
        obj = self.wr.shm.get(oid)
        if obj is not None:
            return True, obj.deserialize(self.wr.ref_factory)
        return False, None

    def ready_local(self, oid: str) -> Optional[bool]:
        """None = not owned; else readiness of the owned object."""
        with self.lock:
            dr = self.results.get(oid)
        if dr is None:
            return None
        return dr.event.is_set()
