"""Cluster telemetry plane: pushed metrics, head-side aggregation, and a
per-process crash flight recorder.

ray: the reference's observability layer is three pipelines — per-worker
TaskEventBuffer batches task state transitions into a GCS-side ring buffer
(gcs_task_manager.h:61), OpenCensus stats export to Prometheus through the
metrics agent (metrics_agent.py:375), and per-component event files
(src/ray/util/event.h).  This module is that layer for this build:

  * PUSH — every process snapshots its util/metrics registry plus its
    wire counters on a period (RAY_TPU_METRICS_PUSH_MS) and ships the
    snapshot to the head as a DROPPABLE oneway riding the v2 batch
    frames: telemetry never competes with ownership traffic (seals,
    refops) for the reconnect backlog, and a dead conn just loses a tick;
  * SINK — the head keeps the latest snapshot per process and folds them
    into bounded ring-buffer time series (the GcsTaskManager ring-storage
    idiom applied to metrics), exposed through util/state.py, the
    dashboard's Prometheus endpoint, and the `ray_tpu metrics` /
    `ray_tpu status` CLI verbs;
  * FLIGHT RECORDER — a bounded in-process ring of recent telemetry
    events (spans, metric-push deltas, fault injections, cluster events)
    in EVERY process, dumped to per-pid JSONL files under
    RAY_TPU_FLIGHT_DIR on an uncaught exception, a lock-watchdog report,
    or a fault-plane `crash` kill — so a chaos-soak death is diagnosable
    from what the process saw in its last seconds, without a replay.

The ring always records (a deque append per event, at flush/tick
granularity — not per task); only the DUMP is gated on the dir knob.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

_ring_lock = threading.Lock()
_ring: Optional[deque] = None
_ring_pid = os.getpid()
_proc_tag = "main"
_installed = False
_dump_seq = 0


def _get_ring() -> deque:
    """Ring, lazily sized from config (and re-created after a fork: the
    parent's entries describe the parent's life, not this process's)."""
    global _ring, _ring_pid
    with _ring_lock:
        if _ring is None or _ring_pid != os.getpid():
            from ray_tpu._private import config as _config

            _ring = deque(maxlen=max(_config.get("flight_ring_size"), 16))
            _ring_pid = os.getpid()
        return _ring


def note(kind: str, **fields: Any) -> None:
    """Record one flight-recorder event.  Never raises — observability
    must not take the process down."""
    try:
        ev = {"t": time.time(), "kind": kind}
        ev.update(fields)
        ring = _get_ring()
        with _ring_lock:
            ring.append(ev)
    except Exception:
        pass


def flight_dir() -> str:
    from ray_tpu._private import config as _config

    return _config.get("flight_dir")


def flight_dump(reason: str) -> Optional[str]:
    """Dump the ring to a per-pid JSONL file under the flight dir (one
    file per process, appended: a process that trips twice keeps both
    dumps).  Returns the path, or None when dumping is disabled/fails.
    Called from crash paths — must never raise and must stay signal-lean
    (plain open/write, no locks beyond the ring's)."""
    global _dump_seq
    d = flight_dir()
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        with _ring_lock:
            events = list(_ring or ())
        # The profiler's top stacks ride every dump: a chaos-killed
        # process records not just what it did but where its time went
        # (None when nothing was sampled — dumping must never block on
        # or require the profiler).
        prof = None
        try:
            from ray_tpu._private import profiler as _profiler

            prof = _profiler.flight_snapshot()
        except Exception:
            prof = None
        _dump_seq += 1
        path = os.path.join(d, f"flight-{os.getpid()}.jsonl")
        with open(path, "a") as f:
            f.write(
                json.dumps(
                    {
                        "kind": "dump",
                        "reason": reason,
                        "pid": os.getpid(),
                        "proc": _proc_tag,
                        "t": time.time(),
                        "seq": _dump_seq,
                        "events": len(events),
                        "prof_stacks": len(prof) if prof else 0,
                    }
                )
                + "\n"
            )
            for ev in events:
                f.write(json.dumps(ev, default=str) + "\n")
            if prof:
                f.write(
                    json.dumps(
                        {
                            "kind": "prof_snapshot",
                            "t": time.time(),
                            "stacks": [[s, n] for s, n in prof],
                        }
                    )
                    + "\n"
                )
        return path
    except Exception:
        return None


def collect_dumps(d: str) -> List[Dict[str, Any]]:
    """Every dump header written by any process into dir `d` (the soak
    harness attaches these to failing reports)."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for fn in names:
        if not (fn.startswith("flight-") and fn.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(d, fn)) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("kind") == "dump":
                        rec["file"] = fn
                        out.append(rec)
        except OSError:
            pass
    return out


def install(tag: Optional[str] = None) -> None:
    """Arm the flight recorder's dump triggers in this process:

      * sys.excepthook / threading.excepthook — an uncaught exception
        dumps before the default handler prints it;
      * faults.point `crash` — the pre-SIGKILL hook dumps the ring at the
        exact hazard site the fault plane killed (the chaos soak's
        worker/daemon/head deaths become diagnosable);
      * lock_watchdog reports — an order inversion or long hold dumps the
        ring alongside the watchdog's own report file.

    Idempotent; cheap enough to call at every process entry."""
    global _installed, _proc_tag
    if tag:
        _proc_tag = tag
    # Sampling-profiler autostart (RAY_TPU_PROF_HZ > 0): every process
    # entry funnels through install(), so the always-hot mode covers
    # head, workers, daemons, and io shards with one knob.  Re-checked
    # per call — forked children re-install under their own tag and the
    # parent's sampler thread did not survive the fork.
    try:
        from ray_tpu._private import profiler as _profiler

        _profiler.maybe_autostart()
    except Exception:
        pass
    if _installed:
        return
    _installed = True

    from ray_tpu._private import faults, lock_watchdog

    faults.set_crash_hook(
        lambda point_name: flight_dump(f"fault-crash:{point_name}")
    )
    lock_watchdog.set_report_hook(
        lambda report: flight_dump("lock-watchdog")
    )

    prev_except = sys.excepthook

    def _excepthook(etype, value, tb):
        note("uncaught", error=f"{etype.__name__}: {value}")
        flight_dump(f"uncaught:{etype.__name__}")
        prev_except(etype, value, tb)

    sys.excepthook = _excepthook

    prev_thread = threading.excepthook

    def _thread_excepthook(args):
        note(
            "uncaught-thread",
            error=f"{args.exc_type.__name__}: {args.exc_value}",
            thread=getattr(args.thread, "name", "?"),
        )
        flight_dump(f"uncaught-thread:{args.exc_type.__name__}")
        prev_thread(args)

    threading.excepthook = _thread_excepthook


# ---------------------------------------------------------------------------
# per-process metric snapshots (the push payload)

_last_push_wire: Dict[str, int] = {}


def snapshot_process(extra: Optional[Dict[str, float]] = None) -> Dict:
    """One process's telemetry snapshot: the full util/metrics registry
    (histograms carry boundaries for head-side rendering), this process's
    wire counters, and any caller-supplied internal gauges (head queue
    depths, journal counters...).  Shipped verbatim as the metrics_push
    payload — pickle carries the tag-tuple keys fine."""
    from ray_tpu._private import wire as _wire
    from ray_tpu.util import metrics as _metrics

    snap = {
        "pid": os.getpid(),
        "proc": _proc_tag,
        "t": time.time(),
        "metrics": _metrics.collect(),
        "wire": _wire.stats(),
    }
    if extra:
        snap["internal"] = dict(extra)
    # Flight-ring the push DELTA (bytes/frames moved since the last one):
    # a crash dump then shows the process's recent control-plane activity.
    try:
        w = snap["wire"]
        global _last_push_wire
        note(
            "metrics_push",
            frames=w["logical_frames"] - _last_push_wire.get("logical_frames", 0),
            writes=w["physical_writes"] - _last_push_wire.get("physical_writes", 0),
            bytes=w["bytes_written"] - _last_push_wire.get("bytes_written", 0),
            metrics=len(snap["metrics"]),
        )
        _last_push_wire = dict(w)
    except Exception:
        pass
    return snap


# ---------------------------------------------------------------------------
# head-side sink: latest snapshot per process + ring-buffer time series

def _flat_key(name: str, tag_key: Tuple) -> str:
    if not tag_key:
        return name
    tags = ",".join(f"{k}={v}" for k, v in tag_key)
    return f"{name}{{{tags}}}"


class TelemetrySink:
    """Aggregates pushed per-process snapshots on the head.

    `processes` holds the LATEST snapshot per sender (worker id, driver
    id, daemon:<node>, "head"); `series` holds bounded (t, value) rings
    per aggregated scalar, appended by sample() at the head's push tick.
    Counters and histogram buckets SUM across processes; gauges sum too
    (queue depths add up — the per-process value stays readable in
    `processes`)."""

    def __init__(self, ring_samples: int = 360):
        self._lock = threading.Lock()
        self.processes: Dict[str, Dict] = {}
        self.series: Dict[str, deque] = {}
        self._ring_samples = max(ring_samples, 4)

    def ingest(self, key: str, snap: Dict) -> None:
        if not isinstance(snap, dict):
            return
        with self._lock:
            # Bounded: a pathological sender churn (worker ids are fresh
            # per spawn) must not grow the map forever.
            while len(self.processes) >= 4096:
                self.processes.pop(next(iter(self.processes)))
            self.processes[key] = snap

    def forget(self, key: str) -> None:
        with self._lock:
            self.processes.pop(key, None)

    def aggregate(self) -> Dict[str, Dict]:
        """Merge the latest snapshots: metric name -> {type, description,
        boundaries?, data: {tag_key: merged value}} — the same shape one
        process's collect() has, so renderers handle both."""
        with self._lock:
            snaps = list(self.processes.values())
        out: Dict[str, Dict] = {}
        for snap in snaps:
            for name, rec in (snap.get("metrics") or {}).items():
                cur = out.get(name)
                if cur is None:
                    cur = out[name] = {
                        "type": rec.get("type"),
                        "description": rec.get("description", ""),
                        "data": {},
                    }
                    if "boundaries" in rec:
                        cur["boundaries"] = rec["boundaries"]
                elif cur.get("type") != rec.get("type"):
                    continue  # name collision across processes: first wins
                for k, v in (rec.get("data") or {}).items():
                    prev = cur["data"].get(k)
                    if prev is None:
                        cur["data"][k] = (
                            dict(v) if isinstance(v, dict) else v
                        )
                    elif isinstance(v, dict):  # histogram series
                        if len(prev.get("buckets", ())) == len(v.get("buckets", ())):
                            prev["buckets"] = [
                                a + b for a, b in zip(prev["buckets"], v["buckets"])
                            ]
                            prev["sum"] = prev.get("sum", 0.0) + v.get("sum", 0.0)
                            prev["count"] = prev.get("count", 0) + v.get("count", 0)
                    else:
                        cur["data"][k] = prev + v
        return out

    def scalars(self) -> Dict[str, float]:
        """Flattened aggregate: one number per (metric, tag set).  The
        series rings and the CLI read this."""
        out: Dict[str, float] = {}
        for name, rec in self.aggregate().items():
            for k, v in rec["data"].items():
                if isinstance(v, dict):
                    out[_flat_key(name + "_count", k)] = float(v.get("count", 0))
                    out[_flat_key(name + "_sum", k)] = float(v.get("sum", 0.0))
                else:
                    out[_flat_key(name, k)] = float(v)
        return out

    def internal_totals(self) -> Dict[str, float]:
        """Cluster-wide sums of the per-process `internal` gauges (head
        queue depths, journal counters) and wire counters."""
        out: Dict[str, float] = {}
        with self._lock:
            snaps = list(self.processes.values())
        for snap in snaps:
            for k, v in (snap.get("internal") or {}).items():
                out[k] = out.get(k, 0.0) + float(v)
            for k, v in (snap.get("wire") or {}).items():
                out[f"wire_{k}"] = out.get(f"wire_{k}", 0.0) + float(v)
        return out

    def sample(self, extra: Optional[Dict[str, float]] = None) -> None:
        """Fold the current aggregate into the time-series rings (one
        sample per metric per head push tick)."""
        now = time.time()
        values = self.scalars()
        values.update(self.internal_totals())
        if extra:
            values.update(extra)
        with self._lock:
            for k, v in values.items():
                ring = self.series.get(k)
                if ring is None:
                    ring = self.series[k] = deque(maxlen=self._ring_samples)
                ring.append((now, v))

    def series_snapshot(
        self, name: Optional[str] = None
    ) -> Dict[str, List[Tuple[float, float]]]:
        with self._lock:
            if name is not None:
                return {name: list(self.series.get(name, ()))}
            return {k: list(v) for k, v in self.series.items()}

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            procs = {
                key: {
                    "pid": s.get("pid"),
                    "proc": s.get("proc"),
                    "age_s": round(time.time() - s.get("t", 0.0), 3),
                    "metrics": len(s.get("metrics") or ()),
                    # Per-process internal gauges ride along (io-shard conn
                    # counts, head queue depths): `ray_tpu status` reads
                    # them per process, not just as cluster sums.
                    **(
                        {"internal": dict(s["internal"])}
                        if isinstance(s.get("internal"), dict)
                        else {}
                    ),
                }
                for key, s in self.processes.items()
            }
            n_series = len(self.series)
        return {
            "processes": procs,
            "series_tracked": n_series,
            "aggregate": self.scalars(),
            "internal": self.internal_totals(),
        }


def prometheus_cluster_text(
    sink: TelemetrySink, extra_gauges: Optional[Dict[str, float]] = None
) -> str:
    """Prometheus text exposition of the CLUSTER aggregate: every pushed
    process registry merged (counters/buckets summed), plus runtime-level
    gauges — the head's /metrics endpoint body (ray: the metrics agent
    re-exports every worker's OpenCensus views the same way)."""
    from ray_tpu.util.metrics import (
        _prom_help,
        _prom_histogram_lines,
        _prom_labels,
        _prom_name,
    )

    agg = sink.aggregate()
    lines: List[str] = []
    for name, rec in sorted(agg.items()):
        pname = _prom_name(name)
        mtype = rec.get("type")
        if mtype == "Counter":
            lines.append(f"# HELP {pname}_total {_prom_help(rec['description'])}")
            lines.append(f"# TYPE {pname}_total counter")
            for k, v in sorted(rec["data"].items()):
                lines.append(f"{pname}_total{_prom_labels(k)} {v}")
        elif mtype == "Gauge":
            lines.append(f"# HELP {pname} {_prom_help(rec['description'])}")
            lines.append(f"# TYPE {pname} gauge")
            for k, v in sorted(rec["data"].items()):
                lines.append(f"{pname}{_prom_labels(k)} {v}")
        elif mtype == "Histogram" and rec.get("boundaries"):
            lines.append(f"# HELP {pname} {_prom_help(rec['description'])}")
            lines.append(f"# TYPE {pname} histogram")
            for k, d in sorted(rec["data"].items()):
                if isinstance(d, dict):
                    lines.extend(
                        _prom_histogram_lines(pname, k, rec["boundaries"], d)
                    )
    for name, value in sorted((extra_gauges or {}).items()):
        pname = _prom_name(f"ray_tpu_{name}")
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {value}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# task lifecycle attribution: the per-task state machine's pure core
#
# ray: gcs_task_manager.h keeps per-task state-transition records (the
# task_events ring here); PAPERS.md's Dapper lineage argues the useful
# unit is the STAGE-ATTRIBUTED record, not aggregate counters.  Each
# TaskRecord carries wall-clock stamps for the stages below (head clock;
# executor stamps land via the done message, clock-offset-corrected);
# stage_durations() telescopes them into per-stage seconds, so the sum
# of durations equals last-stamp minus first-stamp by construction —
# the ≥95%-accounted acceptance property.

# Stamp order (a task flows left to right; absent stamps are skipped):
#   submit     submit_task entry (head)
#   queued     dependencies met, joined the ready queue (head)
#   leased     worker handle acquired by the dispatcher (head)
#   pushed     task frame written to a live conn (head)
#   received   executor dequeued the frame (worker, corrected)
#   running    executor began user code (worker, corrected)
#   exec_done  user code returned (worker, corrected)
#   done       done message landed on the head (head)
#   sealed     results stored + lineage recorded (head)
STAGE_ORDER = (
    "submit", "queued", "leased", "pushed", "received", "running",
    "exec_done", "done", "sealed",
)

# Duration labels: time spent BETWEEN stamp X and the next present stamp
# is attributed to the stage named here (what the task was waiting on).
STAGE_LABELS = {
    "submit": "pending",        # dependency wait
    "queued": "queued",         # scheduler queue
    "leased": "lease",          # worker acquisition (spawn on a cold pool)
    "pushed": "wire",           # frame flight + executor pickup
    "received": "exec_queue",   # executor-side queue behind earlier tasks
    "running": "running",       # user code
    "exec_done": "return",      # result flight back (batch linger + decode)
    "done": "seal",             # head-side store + lineage bookkeeping
}


def stage_durations(stages: Dict[str, float]) -> Dict[str, float]:
    """Telescoped per-stage seconds from a stamp dict (pure).  Negative
    gaps (clock-offset estimation error across processes) clamp to 0 —
    the clamped time reappears in the next head-side stage, so the total
    stays within the offset error of wall time."""
    present = [
        (s, stages[s])
        for s in STAGE_ORDER
        if isinstance(stages.get(s), (int, float))
    ]
    out: Dict[str, float] = {}
    for (s0, t0), (_s1, t1) in zip(present, present[1:]):
        out[STAGE_LABELS.get(s0, s0)] = round(max(t1 - t0, 0.0), 6)
    return out


def stage_wall_seconds(stages: Dict[str, float]) -> float:
    """First-to-last stamped wall time (the denominator of the
    accounted-fraction acceptance check)."""
    ts = [
        stages[s] for s in STAGE_ORDER
        if isinstance(stages.get(s), (int, float))
    ]
    return max(ts[-1] - ts[0], 0.0) if len(ts) >= 2 else 0.0


_STAGE_HIST = None


def task_stage_histogram():
    """`task_stage_seconds{stage=...}` — the head observes every finished
    task's per-stage durations here; the cluster aggregate renders it on
    /metrics.  Lazy: only the process folding task records registers it."""
    global _STAGE_HIST
    if _STAGE_HIST is None:
        from ray_tpu.util.metrics import Histogram

        _STAGE_HIST = Histogram(
            "task_stage_seconds",
            "per-task time spent in each lifecycle stage "
            "(submit→queued→leased→pushed→running→done→sealed machine)",
            boundaries=[0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0, 30.0],
            tag_keys=("stage",),
        )
    return _STAGE_HIST


_REMESH_HIST = None


def remesh_histogram():
    """`remesh_seconds{stage=...}` — elastic-SPMD recovery wall clock
    attributed per stage (detect → teardown → replan → respawn → resume,
    plus total).  The trainer driver observes one sample per stage per
    re-mesh episode; the chaos soak asserts the breakdown lands.  Lazy,
    like task_stage_histogram: only a process that actually re-meshes
    registers it.  Boundaries are seconds-scale: recovery is dominated by
    the replacement-wait policy and worker respawn, not micro latencies."""
    global _REMESH_HIST
    if _REMESH_HIST is None:
        from ray_tpu.util.metrics import Histogram

        _REMESH_HIST = Histogram(
            "remesh_seconds",
            "elastic MESH gang recovery time per stage "
            "(detect/teardown/replan/respawn/resume/total)",
            boundaries=[0.05, 0.2, 0.5, 1.0, 2.0, 5.0, 15.0, 60.0, 300.0],
            tag_keys=("stage",),
        )
    return _REMESH_HIST


_AUTOSCALE_HIST = None


def autoscale_histogram():
    """`autoscale_seconds{stage=...}` — elastic-capacity transition wall
    clock attributed per node-lifecycle edge (launch = REQUESTED→ACTIVE,
    drain_wait = DRAINING→quiesced, evacuate = quiesced→objects-safe,
    depart = DRAINING→DEPARTED, plus total for a full drain).  The
    head-side reconciler observes one sample per transition per node;
    the autoscale chaos soak asserts the breakdown lands.  Lazy like
    remesh_histogram — only a head that actually autoscales registers
    it.  Seconds-scale boundaries: launches are dominated by daemon
    boot, drains by task completion and evacuation."""
    global _AUTOSCALE_HIST
    if _AUTOSCALE_HIST is None:
        from ray_tpu.util.metrics import Histogram

        _AUTOSCALE_HIST = Histogram(
            "autoscale_seconds",
            "elastic-capacity node transition time per stage "
            "(launch/drain_wait/evacuate/depart/total)",
            boundaries=[0.05, 0.2, 0.5, 1.0, 2.0, 5.0, 15.0, 60.0, 300.0],
            tag_keys=("stage",),
        )
    return _AUTOSCALE_HIST


def summarize_task_events(
    events: List[Dict[str, Any]],
    live: Optional[List[Dict[str, Any]]] = None,
    slow: int = 10,
) -> Dict[str, Any]:
    """Fold task records into the `ray_tpu tasks --summary` body (pure):
    per-stage totals + percentiles, the accounted-vs-wall fraction, state
    counts, and the N slowest tasks with their stage breakdowns."""
    per_stage: Dict[str, List[float]] = {}
    states: Dict[str, int] = {}
    wall_total = 0.0
    accounted_total = 0.0
    rows: List[Dict[str, Any]] = []
    for e in events:
        states[e.get("state", "?")] = states.get(e.get("state", "?"), 0) + 1
        durs = e.get("durations") or {}
        stages = e.get("stages") or {}
        wall = stage_wall_seconds(stages) or float(e.get("duration") or 0.0)
        acc = sum(durs.values())
        wall_total += wall
        accounted_total += acc
        for k, v in durs.items():
            per_stage.setdefault(k, []).append(float(v))
        rows.append(
            {
                "task_id": e.get("task_id"),
                "name": e.get("name"),
                "state": e.get("state"),
                "wall_s": round(wall, 6),
                "durations": durs,
                "creation": bool(e.get("creation")),
                "critical_stage": (
                    max(durs, key=durs.get) if durs else None
                ),
            }
        )
    for t in live or ():
        states[t.get("state", "?")] = states.get(t.get("state", "?"), 0) + 1

    def _pct(xs: List[float], q: float) -> float:
        if not xs:
            return 0.0
        xs = sorted(xs)
        return xs[min(int(q * (len(xs) - 1) + 0.5), len(xs) - 1)]

    stage_stats = {
        k: {
            "count": len(v),
            "total_s": round(sum(v), 6),
            "mean_s": round(sum(v) / len(v), 6),
            "p50_s": round(_pct(v, 0.50), 6),
            "p95_s": round(_pct(v, 0.95), 6),
            "p99_s": round(_pct(v, 0.99), 6),
        }
        for k, v in sorted(per_stage.items())
    }
    rows.sort(key=lambda r: -r["wall_s"])
    return {
        "tasks": len(events),
        "states": states,
        "stages": stage_stats,
        "wall_s_total": round(wall_total, 6),
        "accounted_s_total": round(accounted_total, 6),
        "accounted_fraction": (
            round(accounted_total / wall_total, 4) if wall_total else None
        ),
        "slow": rows[: max(slow, 0)],
    }


# ---------------------------------------------------------------------------
# object/memory introspection plane: bytes-per-copy counters + the ledger

def _copy_counters():
    """Process-wide bytes-per-copy counters, created lazily (module import
    runs before config/metric setup in some entrypoints).  Every byte-
    moving path of the object plane increments these: put/seal (create a
    sealed copy), pull (a transfer-plane copy from a sealed source),
    relay (a transfer-plane copy served out of an in-flight pull's board
    — pipelined broadcast), spill/restore (disk round trips), promote
    (inline bytes uploaded to the head), arena_map (a same-node zero-copy
    map of a sealed arena buffer: copies tick, bytes stay ZERO — the
    counted proof reads don't copy).  The copy-coverage lint pass holds
    every byte-moving function in store/object_plane/arena to this
    counter (or a reviewed allowlist entry).  ray_perf's put/broadcast
    shapes report bytes-per-copy off the deltas; the cluster aggregate
    sums every process's counts via the metrics push."""
    global _OBJ_COPIES, _OBJ_COPY_BYTES
    if _OBJ_COPIES is None:
        from ray_tpu.util.metrics import Counter

        _OBJ_COPIES = Counter(
            "object_copies",
            "sealed-copy operations by object-plane path",
            tag_keys=("path",),
        )
        _OBJ_COPY_BYTES = Counter(
            "object_copy_bytes",
            "bytes moved per object-plane copy path",
            tag_keys=("path",),
        )
    return _OBJ_COPIES, _OBJ_COPY_BYTES


_OBJ_COPIES = None
_OBJ_COPY_BYTES = None


def count_copy(path: str, nbytes: int) -> None:
    """Record one object-plane copy of nbytes via `path` (put/seal/pull/
    relay/spill/restore/promote/arena_map).  Never raises — called from
    store/transfer hot paths, sometimes under their locks."""
    try:
        copies, by = _copy_counters()
        copies.inc(tags={"path": path})
        if nbytes:
            by.inc(nbytes, tags={"path": path})
    except Exception:
        pass


def copy_counter_snapshot() -> Dict[str, Dict[str, float]]:
    """{path: {copies, bytes}} from this process's counters (ray_perf
    reads deltas of this around a timed shape)."""
    out: Dict[str, Dict[str, float]] = {}
    try:
        copies, by = _copy_counters()
        for k, v in copies.snapshot().items():
            path = dict(k).get("path", "?")
            out.setdefault(path, {"copies": 0.0, "bytes": 0.0})["copies"] = v
        for k, v in by.snapshot().items():
            path = dict(k).get("path", "?")
            out.setdefault(path, {"copies": 0.0, "bytes": 0.0})["bytes"] = v
    except Exception:
        pass
    return out


_LEDGER_GAUGES = None


def ledger_gauges():
    """Prometheus-facing gauges the head sets from its ledger tick:
    per-node store/spilled bytes and per-node leak-suspect bytes.  Lazy —
    only the process that sets them registers them."""
    global _LEDGER_GAUGES
    if _LEDGER_GAUGES is None:
        from ray_tpu.util.metrics import Gauge

        _LEDGER_GAUGES = (
            Gauge(
                "object_ledger_node_bytes",
                "sealed object bytes per node and tier (store/spilled), "
                "from the head's object-ledger join",
                tag_keys=("node", "tier"),
            ),
            Gauge(
                "object_ledger_leak_suspect_bytes",
                "bytes attributed to object-ledger leak suspects, by the "
                "holding (or owning) node",
                tag_keys=("node",),
            ),
        )
    return _LEDGER_GAUGES


class ObjectLedger:
    """Head-side sink for pushed per-process live-ref tables (refs_push),
    the worker leg of cluster memory introspection.  Mirrors TelemetrySink:
    latest snapshot per sender, forgotten when the process dies.  The
    authoritative owner-side join (store tables + object directory + conn-
    tracked borrows) happens in build_memory_records — this class only
    carries what remote processes report about themselves (in-process
    counts, owned flags, creation sites)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.tables: Dict[str, Dict] = {}

    def ingest(self, key: str, snap: Dict) -> None:
        if not isinstance(snap, dict):
            return
        with self._lock:
            while len(self.tables) >= 4096:
                self.tables.pop(next(iter(self.tables)))
            self.tables[key] = snap

    def forget(self, key: str) -> None:
        with self._lock:
            self.tables.pop(key, None)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return dict(self.tables)


def build_memory_records(
    store_table: Dict[str, Tuple[str, Optional[int]]],
    refcounts: Dict[str, int],
    ready: Dict[str, bool],
    locations: Dict[str, List[str]],
    sizes: Dict[str, int],
    meta: Dict[str, Tuple[float, str]],
    conn_refs: Dict[str, Dict[str, int]],
    pushed_tables: Dict[str, Dict],
    dead_refs: Dict[str, Dict],
    proc_info: Dict[str, Tuple[Optional[str], Optional[int]]],
    now: float,
    leak_age_s: float,
) -> List[Dict[str, Any]]:
    """Join the owner's view of every object with the holder-side ref
    tables into per-object ledger records (pure — unit-testable without a
    cluster).

      store_table    oid -> (location, size|None) from OwnerStore (head
                     bytes: memory/shm/spilled/error)
      refcounts      owner-side refcount per oid
      locations      oid -> [node ids] holding sealed remote copies
      sizes          oid -> packed size (survives spill)
      meta           oid -> (created_ts, creator proc label)
      conn_refs      holder key -> {oid: outstanding conn-tracked borrows}
                     (workers via refop tracking, drivers via driver_refs,
                     "head" for the head process's own live-ref table)
      pushed_tables  holder key -> refs_push snapshot ({"refs": {oid:
                     [count, site]}, ...}) — enrichment (sites, owned)
      dead_refs      crashed holder key -> {"refs", "node", "pid", ...}:
                     borrows awaiting reclaim — their objects are the
                     DEAD-HOLDER leak suspects
      proc_info      holder key -> (node, pid) for live holders

    Leak rules (SURVEY §2.1's debugging story):
      * dead-holder — bytes still held by a crashed process's unreclaimed
        borrows (clears when the reclaim sweep drops them);
      * no-live-holder — located bytes, refcount 0, no holder anywhere,
        older than leak_age_s (outside the seal-to-first-addref window).
    """
    oids = set(store_table) | set(locations) | set(refcounts)
    pushed_refs: Dict[str, Dict] = {}
    for key, snap in pushed_tables.items():
        refs = snap.get("refs") if isinstance(snap, dict) else None
        if refs:
            pushed_refs[key] = refs
            oids.update(refs)
    for rec in dead_refs.values():
        oids.update(rec.get("refs", ()))

    records: List[Dict[str, Any]] = []
    for oid in oids:
        loc, size = store_table.get(oid, (None, None))
        if size is None:
            size = sizes.get(oid)
        copies = list(locations.get(oid, ()))
        if loc in ("memory", "shm", "spilled"):
            copies = ["head"] + copies
        if loc is None:
            loc = "remote" if locations.get(oid) else "worker-local"
        holders: List[Dict[str, Any]] = []
        for key, table in conn_refs.items():
            n = table.get(oid)
            if not n:
                continue
            node, pid = proc_info.get(key, (None, None))
            pushed = pushed_refs.get(key, {}).get(oid)
            holders.append(
                {
                    "holder": key,
                    "node": node,
                    "pid": pid,
                    "count": n,
                    "site": pushed[1] if pushed else None,
                    "owned": bool(pushed[2]) if pushed and len(pushed) > 2 else False,
                    "pinned": bool(pushed[3]) if pushed and len(pushed) > 3 else False,
                    "dead": False,
                }
            )
        seen = {h["holder"] for h in holders}
        for key, refs in pushed_refs.items():
            # Processes whose borrows are not conn-tracked (e.g. owned
            # direct-call results that never escaped) still show as
            # holders via their pushed table.
            if key in seen or oid not in refs:
                continue
            node, pid = proc_info.get(key, (None, None))
            rec = refs[oid]
            holders.append(
                {
                    "holder": key,
                    "node": node,
                    "pid": pid,
                    "count": rec[0],
                    "site": rec[1],
                    "owned": bool(rec[2]) if len(rec) > 2 else False,
                    "pinned": bool(rec[3]) if len(rec) > 3 else False,
                    "dead": False,
                }
            )
        leak = None
        for key, rec in dead_refs.items():
            n = rec.get("refs", {}).get(oid)
            if n:
                holders.append(
                    {
                        "holder": key,
                        "node": rec.get("node"),
                        "pid": rec.get("pid"),
                        "count": n,
                        "site": None,
                        "owned": False,
                        "pinned": False,
                        "dead": True,
                    }
                )
                # Only a suspect while the owner still accounts the
                # object (bytes or count) — a freed oid lingering in the
                # dead set until the sweep is not a leak.
                if (
                    refcounts.get(oid, 0) > 0
                    or oid in store_table
                    or locations.get(oid)
                ):
                    leak = "dead-holder"
        created, creator = meta.get(oid, (None, None))
        age = round(now - created, 3) if created else None
        has_bytes = loc in ("memory", "shm", "spilled") or bool(
            locations.get(oid)
        )
        if (
            leak is None
            and has_bytes
            and refcounts.get(oid, 0) == 0
            and not holders
            and ready.get(oid, False)
            and (age is None or age > leak_age_s)
        ):
            leak = "no-live-holder"
        records.append(
            {
                "object_id": oid,
                "location": loc,
                "size_bytes": size,
                "copies": copies,
                "refcount": refcounts.get(oid, 0),
                "ready": bool(ready.get(oid, False)),
                "holders": holders,
                "holder_count": sum(h["count"] for h in holders),
                "age_s": age,
                "creator": creator,
                "site": next(
                    (h["site"] for h in holders if h["site"]), None
                ),
                "leak": leak,
            }
        )
    records.sort(key=lambda r: -(r["size_bytes"] or 0))
    return records


def summarize_memory_records(
    records: List[Dict[str, Any]],
    group_by: Optional[str] = None,
    top: int = 20,
) -> Dict[str, Any]:
    """Aggregations over ledger records: per-node bytes, top-N objects,
    leak suspects, optional group-by (node|owner|callsite) — the body of
    `ray_tpu memory`, util/state.memory_summary and /api/memory."""
    nodes: Dict[str, Dict[str, float]] = {}
    total = 0
    spilled = 0
    for r in records:
        size = r["size_bytes"] or 0
        total += size
        for node in r["copies"] or (
            [h["node"] or "?" for h in r["holders"]][:1] or ["?"]
        ):
            rec = nodes.setdefault(
                node, {"store_bytes": 0, "spilled_bytes": 0, "objects": 0}
            )
            rec["objects"] += 1
            if r["location"] == "spilled" and node == "head":
                rec["spilled_bytes"] += size
                spilled += size
            else:
                rec["store_bytes"] += size
    leaks = [r for r in records if r["leak"]]
    out: Dict[str, Any] = {
        "objects": len(records),
        "bytes_total": total,
        "spilled_bytes": spilled,
        "nodes": nodes,
        "top": records[: max(top, 0)],
        "leak_suspects": len(leaks),
        "leak_suspect_bytes": sum(r["size_bytes"] or 0 for r in leaks),
        "leaks": leaks,
    }
    if group_by:
        groups: Dict[str, Dict[str, float]] = {}

        def keys_for(r) -> List[str]:
            if group_by == "node":
                return [str(k) for k in (r["copies"] or ["?"])]
            if group_by == "owner":
                return [str(r["creator"] or "?")]
            if group_by == "callsite":
                sites = {h["site"] for h in r["holders"] if h["site"]}
                if r["site"]:
                    sites.add(r["site"])
                return [str(s) for s in (sites or {"?"})]
            raise ValueError(
                f"unknown group_by {group_by!r} (node|owner|callsite)"
            )

        for r in records:
            for k in keys_for(r):
                g = groups.setdefault(k, {"objects": 0, "bytes": 0})
                g["objects"] += 1
                g["bytes"] += r["size_bytes"] or 0
        out["groups"] = dict(
            sorted(groups.items(), key=lambda kv: -kv[1]["bytes"])
        )
    return out


def _reset_for_tests() -> None:
    global _ring, _last_push_wire
    with _ring_lock:
        _ring = None
    _last_push_wire = {}
