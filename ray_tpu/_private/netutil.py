"""Socket tuning + fd passing for control-plane connections.

TCP_NODELAY on every control conn, both ends.  Without it, the
write-write-read pattern the protocol produces (a refop oneway piggybacked
right before a request on the same conn) trips Nagle + delayed-ACK and
turns a sub-millisecond round trip into ~40ms — the reference disables
Nagle on its RPC sockets for the same reason (grpc sets TCP_NODELAY by
default).

The fd-passing pair (send_conn_fd / recv_conn_fd) is the io-shard
handoff primitive: after the auth handshake, the head ships a live
connection's file descriptor to an io-shard process over an AF_UNIX
channel (SCM_RIGHTS, the same mechanism multiprocessing.resource_sharer
uses) so the shard takes over the socket without the peer noticing —
same TCP conn, new owning process.
"""

from __future__ import annotations

import os
import socket


def send_conn_fd(channel, fd: int, dest_pid: int) -> None:
    """Ship a connection's fd over an AF_UNIX Connection (SCM_RIGHTS).
    The caller still owns (and must close) its copy of `fd`; the receiver
    gets an independent duplicate."""
    from multiprocessing import reduction

    reduction.send_handle(channel, fd, dest_pid)


def recv_conn_fd(channel):
    """Receive a handed-off connection fd and rebuild a live
    multiprocessing Connection around it (read+write: the io-shard side
    of the handoff owns both directions of the socket)."""
    from multiprocessing import reduction
    from multiprocessing.connection import Connection

    fd = reduction.recv_handle(channel)
    return Connection(fd)


def send_fd(channel, fd: int, dest_pid: int) -> None:
    """Ship a PLAIN file descriptor (not a connection) over an AF_UNIX
    channel via SCM_RIGHTS — the arena-handoff primitive: a node daemon
    passes its open arena fd to the zygote, whose forked workers inherit
    it and mmap the store without resolving the path.  The caller keeps
    (and must close) its own copy; the receiver gets a duplicate."""
    from multiprocessing import reduction

    reduction.send_handle(channel, fd, dest_pid)


def recv_fd(channel) -> int:
    """Receive a plain fd passed with send_fd; the returned descriptor is
    owned by the caller."""
    from multiprocessing import reduction

    return reduction.recv_handle(channel)


def set_nodelay(conn) -> None:
    """Disable Nagle on a multiprocessing.connection.Connection (TCP only;
    silently no-ops for anything else)."""
    try:
        s = socket.socket(fileno=os.dup(conn.fileno()))
    except OSError:
        return
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    finally:
        s.close()
