"""Socket tuning for control-plane connections.

TCP_NODELAY on every control conn, both ends.  Without it, the
write-write-read pattern the protocol produces (a refop oneway piggybacked
right before a request on the same conn) trips Nagle + delayed-ACK and
turns a sub-millisecond round trip into ~40ms — the reference disables
Nagle on its RPC sockets for the same reason (grpc sets TCP_NODELAY by
default).
"""

from __future__ import annotations

import os
import socket


def set_nodelay(conn) -> None:
    """Disable Nagle on a multiprocessing.connection.Connection (TCP only;
    silently no-ops for anything else)."""
    try:
        s = socket.socket(fileno=os.dup(conn.fileno()))
    except OSError:
        return
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    finally:
        s.close()
