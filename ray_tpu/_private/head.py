"""Standalone head process: the control plane split out of the driver.

ray: src/ray/gcs/gcs_server/gcs_server_main.cc + gcs_server.h:77 — the
reference runs GCS as its own process so driver death never takes down the
cluster.  Here the head process hosts the full Runtime (GlobalState tables,
scheduler, ownership, head object store, worker pools), and DRIVERS become
clients: they attach over TCP with a "driver" hello and speak the same op
protocol workers do (ray: the Ray Client server reuses the core worker
surface the same way, python/ray/util/client/ARCHITECTURE.md).

Consequences, mirroring the reference:
  * kill -9 a driver → the head lives on; the dead driver's refs are
    dropped and its non-detached actors are killed, while
    lifetime="detached" actors keep serving (ray: gcs_actor_manager
    OnJobFinished semantics);
  * a new driver can attach and reach named/detached actors;
  * drivers on OTHER machines attach the same way (no shared store path —
    objects ride the control conn or the transfer plane), which is this
    framework's ray://-client equivalent.

Launch:
    python -m ray_tpu._private.head     (env RAY_TPU_HEAD_CONFIG json)
or programmatically via launch_head_subprocess() (tests/CLI).

The head writes `head.json` ({host, port, authkey, session}) into its
session dir; `ray_tpu.init(address=<path-to-head.json>)` attaches to it.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
from typing import Dict, Optional, Tuple


def head_info_path(session_dir: str) -> str:
    return os.path.join(session_dir, "head.json")


def write_head_info(session_dir: str, rt) -> str:
    os.makedirs(session_dir, exist_ok=True)
    host, port = rt.address
    path = head_info_path(session_dir)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {
                "host": host,
                "port": port,
                "authkey": rt._authkey.hex(),
                "session": rt.session_name,
            },
            f,
        )
    os.replace(tmp, path)
    return path


def read_head_info(path_or_dir: str) -> Dict:
    p = path_or_dir
    if os.path.isdir(p):
        p = head_info_path(p)
    with open(p) as f:
        return json.load(f)


def main() -> None:
    cfg = json.loads(os.environ.get("RAY_TPU_HEAD_CONFIG", "{}"))
    session_dir = cfg.get("session_dir") or "/tmp/raytpu-head"
    # The head's cluster is restart-survivable: daemons/workers retry the
    # head's FIXED address for this window instead of dying on conn EOF.
    os.environ.setdefault("RAY_TPU_RECONNECT_WINDOW_S", "30")
    # Standalone heads default to the crash-safe journaled backend — a
    # restart is this process's reason to exist (ray: GCS FT requires the
    # Redis-backed store; sqlite is our dependency-free analogue).
    os.environ.setdefault("RAY_TPU_GCS_STORAGE_BACKEND", "sqlite")

    # Reuse the previous incarnation's port + authkey (same session) so
    # surviving daemons/workers can find and authenticate to the restarted
    # head — the GCS-address-stability premise of ray's FT story.
    listen_port = int(cfg.get("listen_port") or 0)
    authkey = bytes.fromhex(cfg["authkey"]) if cfg.get("authkey") else None
    if not listen_port:
        try:
            prior = read_head_info(session_dir)
            if cfg.get("session") and prior.get("session") == cfg["session"]:
                listen_port = int(prior["port"])
                authkey = bytes.fromhex(prior["authkey"])
        except (OSError, ValueError, KeyError):
            pass

    from ray_tpu._private import faults
    from ray_tpu._private.runtime import Runtime

    faults.set_process_tag("head")
    rt = Runtime(
        num_cpus=cfg.get("num_cpus"),
        resources=cfg.get("resources"),
        namespace=cfg.get("namespace", "default"),
        session_name=cfg.get("session"),
        snapshot_path=os.path.join(session_dir, "gcs_snapshot.pkl")
        if cfg.get("persist", True)
        else None,
        listen_port=listen_port,
        authkey=authkey,
    )
    # Register the singleton: head-side surfaces that read through
    # get_runtime() — the dashboard/timeline export an attached driver's
    # `ray_tpu timeline` request serves, the state API — work in the
    # standalone head exactly as they do in an in-process driver.
    from ray_tpu._private import runtime as runtime_mod

    runtime_mod._runtime = rt
    write_head_info(session_dir, rt)

    stop = {"flag": False}

    def _term(*_a):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop["flag"]:
        time.sleep(0.2)
    rt.shutdown()
    sys.exit(0)


def launch_head_subprocess(
    session_dir: str,
    num_cpus: int = 4,
    resources: Optional[Dict] = None,
    session: Optional[str] = None,
    persist: bool = True,
    wait_timeout: float = 60.0,
    detach: bool = False,
) -> Tuple[object, str]:
    """Start a head process and wait for its head.json (test/CLI helper).
    Returns (Popen, head_json_path).

    detach=True: own session + stdio to files under session_dir, so the
    head outlives the launcher and holds no inherited pipes open (`ray_tpu
    start --head` — without this, a caller reading the CLI's stdout pipe
    would block until the head itself exits)."""
    import subprocess

    env = os.environ.copy()
    # A restarted head must come back at the SAME address: carry the prior
    # incarnation's port + authkey (if any) into the new process before
    # clearing the stale head.json.
    listen_port, authkey = 0, None
    path = head_info_path(session_dir)
    try:
        prior = read_head_info(path)
        if session and prior.get("session") == session:
            listen_port = int(prior["port"])
            authkey = prior["authkey"]
    except (OSError, ValueError, KeyError):
        pass
    env["RAY_TPU_HEAD_CONFIG"] = json.dumps(
        {
            "session_dir": session_dir,
            "num_cpus": num_cpus,
            "resources": resources or {},
            "session": session,
            "persist": persist,
            "listen_port": listen_port,
            "authkey": authkey,
        }
    )
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    paths = [pkg_root] + [p for p in sys.path if p]
    env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(paths))
    try:
        os.unlink(path)  # a stale file would ack before the head is up
    except OSError:
        pass
    popen_kw = {}
    if detach:
        out = open(os.path.join(session_dir, "head.out"), "ab")
        err = open(os.path.join(session_dir, "head.err"), "ab")
        popen_kw = {"stdout": out, "stderr": err, "start_new_session": True}
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.head"],
        env=env,
        close_fds=True,
        **popen_kw,
    )
    if detach:
        out.close()
        err.close()
    deadline = time.monotonic() + wait_timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return proc, path
        if proc.poll() is not None:
            raise RuntimeError(f"head process exited rc={proc.returncode}")
        time.sleep(0.02)
    proc.terminate()
    raise TimeoutError("head did not write head.json in time")


if __name__ == "__main__":
    main()
