"""Global control state: node/actor/job/function/KV/placement-group tables.

In-process analogue of the reference's GCS server
(ray: src/ray/gcs/gcs_server/gcs_server.h:77) with the same table layout:
  * NodeTable   -- ray: gcs_node_manager.h:41
  * ActorTable  -- ray: gcs_actor_manager.h:280 (restart FSM at :258)
  * FunctionTable -- ray: python/ray/_private/function_manager.py (fn exports)
  * KV          -- ray: gcs_kv_manager.cc
  * PlacementGroupTable -- ray: gcs_placement_group_manager.h:223

The driver process hosts these tables; worker processes reach them through
their connection to the driver (the "DCN control plane"). A future multi-host
round promotes this object behind a gRPC service without changing callers.
"""

from __future__ import annotations

import threading

from ray_tpu._private import lock_watchdog
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

# Actor lifecycle states (ray: gcs_actor_manager.h FSM)
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


@dataclass
class NodeInfo:
    node_id: str
    resources: Dict[str, float]
    available: Dict[str, float]
    alive: bool = True
    labels: Dict[str, str] = field(default_factory=dict)
    is_head: bool = False
    # Scale-down drain (ray: DrainNode RPC / NodeDeathInfo EXPECTED_TERMINATION):
    # a draining node takes no NEW placements — the scheduler filters it from
    # every candidate set — while existing work finishes and still-referenced
    # objects evacuate.  Volatile like the rest of the node table; the durable
    # record is the runtime's journaled node_lifecycle table, which re-marks
    # the flag when a mid-drain daemon re-registers after a head bounce.
    draining: bool = False


@dataclass
class ActorInfo:
    actor_id: str
    name: Optional[str]
    state: str = PENDING_CREATION
    node_id: Optional[str] = None
    worker_id: Optional[str] = None
    max_restarts: int = 0
    num_restarts: int = 0
    creation_spec: Any = None  # TaskSpec, kept for restarts
    death_cause: Optional[str] = None
    namespace: str = "default"
    # Head-split mode (ray: gcs_actor_manager detached actors + job owner):
    # owner_did names the attached driver that created the actor (None for
    # the in-process driver); non-detached actors die with their owner.
    owner_did: Optional[str] = None
    detached: bool = False


@dataclass
class PlacementGroupInfo:
    pg_id: str
    bundles: List[Dict[str, float]]
    strategy: str
    state: str = "PENDING"  # PENDING | CREATED | RESHAPING | REMOVED
    # bundle index -> node_id
    bundle_nodes: Dict[int, str] = field(default_factory=dict)
    # bundle index -> remaining capacity inside the reserved bundle
    # (tasks scheduled into the PG consume bundle capacity, not node pool:
    #  ray: src/ray/raylet/placement_group_resource_manager.h)
    bundle_available: Dict[int, Dict[str, float]] = field(default_factory=dict)
    name: Optional[str] = None
    # Elastic re-mesh (MESH gangs): the full-size bundle list as requested
    # at creation.  `bundles` shrinks to N-1 when a reshape re-plans a
    # smaller box; orig_bundles is what scale-up restores.
    orig_bundles: List[Dict[str, float]] = field(default_factory=list)
    # Bumped on every successful (re)reservation after a reshape; trainers
    # watch it to detect that the gang they joined no longer exists.
    generation: int = 0
    # Node whose death triggered the current RESHAPING episode.
    lost_node: Optional[str] = None
    # Set by the reshape sweep on a shrunk-but-CREATED gang when a full-size
    # box has become plannable again; the trainer opts in via pg_reshape.
    scale_up_ready: bool = False
    # Head-local (NOT persisted): monotonic deadline after which the sweep
    # stops waiting for a replacement host and shrinks the box.  A head
    # bounce mid-RESHAPING resets the wait window on restore.
    reshape_deadline: Optional[float] = None
    # Head-local (NOT persisted): monotonic stamp of when the current
    # RESHAPING episode began — trainers read it via pg_info to attribute
    # the "detect" stage of recovery (monotonic is system-wide on Linux,
    # so driver-side deltas against it are meaningful).
    reshaping_since: Optional[float] = None


def pg_record(info: "PlacementGroupInfo") -> Dict[str, Any]:
    """Persistable dict form of one PG-table row (journal entries and the
    snapshot fold share it, so restore merges them field-for-field).
    Reservation state (bundle_nodes/bundle_available) is deliberately NOT
    persisted: a restored head re-reserves against the rebuilt node table."""
    return {
        "pg_id": info.pg_id,
        "bundles": info.bundles,
        "strategy": info.strategy,
        "state": info.state,
        "name": info.name,
        "orig_bundles": info.orig_bundles,
        "generation": info.generation,
        "lost_node": info.lost_node,
    }


def actor_record(info: "ActorInfo") -> Dict[str, Any]:
    """Persistable dict form of one actor-table row (snapshot AND journal
    use the same shape, so restore merges them field-for-field)."""
    return {
        "actor_id": info.actor_id,
        "name": info.name,
        "namespace": info.namespace,
        "state": info.state,
        "worker_id": info.worker_id,
        "node_id": info.node_id,
        "max_restarts": info.max_restarts,
        "num_restarts": info.num_restarts,
        "detached": info.detached,
        "owner_did": info.owner_did,
        "creation_spec": info.creation_spec,
    }


class GlobalState:
    def __init__(self):
        self.lock = lock_watchdog.make_lock("GlobalState.lock", rlock=True)
        self.nodes: Dict[str, NodeInfo] = {}
        self.actors: Dict[str, ActorInfo] = {}
        self.named_actors: Dict[tuple, str] = {}  # (namespace, name) -> actor_id
        self.functions: Dict[str, bytes] = {}
        self.kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> {key: val}
        self.placement_groups: Dict[str, PlacementGroupInfo] = {}
        # Job table (ray: gcs_job_manager): attached drivers are this
        # build's jobs — job_id == did, transitions RUNNING -> FINISHED.
        self.jobs: Dict[str, Dict[str, Any]] = {}
        self.job_start_time = time.time()
        # Durability hook (runtime._journal_append when the mutation
        # journal is enabled): every actor/named-binding/job mutation the
        # mutators below apply is mirrored into the append-only journal so
        # it survives a head death between snapshot ticks.  The lint's
        # gcs-mutation pass enforces that these tables are only ever
        # written through this module.
        self.journal_hook: Optional[Callable[[tuple], None]] = None
        # Fired (outside the table lock) after every function export:
        # the runtime releases lineage re-executions parked on a pending
        # function-export fence (see Runtime._reconstruct).
        self.on_function_export: Optional[Callable[[str], None]] = None
        # Cluster-event channels on the SHARED pubsub abstraction
        # (ray: src/ray/pubsub/publisher.h:298 — same Publisher the
        # runtime's object-ready plane and serve's long-poll use).
        from ray_tpu._private.pubsub import Publisher

        self.publisher = Publisher()

    def _journal(self, entry: tuple) -> None:
        """Mirror one table mutation into the durability journal (no-op
        until the runtime installs its hook; best-effort by contract —
        the hook swallows I/O failures, the next snapshot re-captures)."""
        hook = self.journal_hook
        if hook is not None:
            hook(entry)

    # -- events --------------------------------------------------------------

    def subscribe(self, channel: str, cb: Callable) -> None:
        self.publisher.subscribe(channel, None, cb)

    def publish(self, channel: str, *args) -> None:
        self.publisher.publish(channel, None, *args)

    # -- nodes ---------------------------------------------------------------

    def register_node(self, info: NodeInfo) -> None:
        with self.lock:
            self.nodes[info.node_id] = info
        self.publish("node_added", info.node_id)

    def remove_node(self, node_id: str) -> None:
        with self.lock:
            n = self.nodes.get(node_id)
            if n:
                n.alive = False
        self.publish("node_removed", node_id)

    def alive_nodes(self) -> List[NodeInfo]:
        with self.lock:
            return [n for n in self.nodes.values() if n.alive]

    def set_node_draining(self, node_id: str, draining: bool = True) -> None:
        """Flip the drain flag on a live node-table row.  NOT journaled:
        the node table is volatile (rebuilt from daemon re-registration),
        and the durable drain record is the runtime's node_lifecycle
        journal kind — restore re-applies this flag from there."""
        with self.lock:
            n = self.nodes.get(node_id)
            if n:
                n.draining = draining

    # -- functions -----------------------------------------------------------

    def export_function(self, fn_id: str, blob: bytes) -> None:
        """Journaled (PR-4 residual closed): a lineage re-execution within
        the first snapshot tick of an export used to hit "unknown
        function" after a head bounce — the journal now carries the blob
        the moment it is exported, not 0.5s later."""
        with self.lock:
            if self.functions.get(fn_id) == blob:
                return  # re-export of the same blob: don't re-journal it
            self.functions[fn_id] = blob
            self._journal(("function", fn_id, blob))
        hook = self.on_function_export
        if hook is not None:
            # OUTSIDE the table lock: the hook takes the runtime lock and
            # the global order is runtime.lock -> state.lock.
            hook(fn_id)

    def import_functions(self, functions: Dict[str, bytes]) -> None:
        """Restore-path bulk load (snapshot merge) — NOT journaled: the
        entries came from the journal/snapshot being replayed."""
        with self.lock:
            self.functions.update(functions)

    def get_function(self, fn_id: str) -> Optional[bytes]:
        with self.lock:
            return self.functions.get(fn_id)

    # -- actors --------------------------------------------------------------

    def register_actor(self, info: ActorInfo) -> None:
        with self.lock:
            if info.name:
                key = (info.namespace, info.name)
                if key in self.named_actors:
                    raise ValueError(f"actor name {info.name!r} already taken")
                self.named_actors[key] = info.actor_id
            self.actors[info.actor_id] = info
            # ALL actor records are durable — anonymous ones too (ray:
            # gcs_actor_manager persists every record; the named binding
            # rides in the same record).
            self._journal(("actor_register", actor_record(info)))

    def get_actor(self, actor_id: str) -> Optional[ActorInfo]:
        with self.lock:
            return self.actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str = "default") -> Optional[ActorInfo]:
        with self.lock:
            aid = self.named_actors.get((namespace, name))
            return self.actors.get(aid) if aid else None

    def set_actor_state(self, actor_id: str, state: str, **kw) -> None:
        with self.lock:
            a = self.actors.get(actor_id)
            if not a:
                return
            a.state = state
            for k, v in kw.items():
                setattr(a, k, v)
            if state == DEAD and a.name:
                self.named_actors.pop((a.namespace, a.name), None)
            # num_restarts is snapshotted with every transition so a
            # journal replay lands the restart budget, not just the state.
            self._journal(
                ("actor_state", actor_id, state,
                 {**kw, "num_restarts": a.num_restarts})
            )
        self.publish("actor_state", actor_id, state)

    # -- placement groups (ray: gcs_placement_group_manager.h) ---------------

    def register_pg(self, info: PlacementGroupInfo) -> None:
        """Journaled PG registration.  Reservation state stays volatile;
        the durable record is the spec + lifecycle state (pg_record)."""
        with self.lock:
            if not info.orig_bundles:
                info.orig_bundles = [dict(b) for b in info.bundles]
            self.placement_groups[info.pg_id] = info
            self._journal(("pg_register", pg_record(info)))

    def set_pg_state(self, pg_id: str, state: str, **kw) -> None:
        """Journaled PG lifecycle transition (PENDING|CREATED|RESHAPING|
        REMOVED) plus any reshape bookkeeping riders (generation,
        lost_node, bundles after a shrink...).  Mutate+journal only — no
        publish: callers hold scheduler.lock (order: scheduler.lock ->
        state.lock) and events go out through the runtime's EventLog."""
        with self.lock:
            pg = self.placement_groups.get(pg_id)
            if not pg:
                return
            pg.state = state
            for k, v in kw.items():
                setattr(pg, k, v)
            self._journal(
                ("pg_state", pg_id, state,
                 {**{k: v for k, v in kw.items()
                     if k not in ("reshape_deadline", "reshaping_since")},
                  "generation": pg.generation})
            )

    def restore_pg(self, info: PlacementGroupInfo) -> None:
        """Restore-path upsert (snapshot merge / journal replay) — NOT
        journaled: the record came from the journal/snapshot being
        replayed."""
        with self.lock:
            self.placement_groups[info.pg_id] = info

    # -- jobs (ray: gcs_job_manager) -----------------------------------------

    def set_job_state(self, job_id: str, state: str, **kw) -> None:
        """Journaled job-table transition (attached drivers are the jobs:
        RUNNING at attach, FINISHED at death/detach).  Restore replays
        these so a restarted head knows which owners were already gone."""
        with self.lock:
            rec = self.jobs.setdefault(job_id, {"job_id": job_id})
            rec["state"] = state
            rec.update(kw)
            self._journal(("job_state", job_id, state, dict(kw)))

    def get_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        with self.lock:
            rec = self.jobs.get(job_id)
            return dict(rec) if rec else None

    # -- kv (ray: gcs_kv_manager.cc) ----------------------------------------

    def kv_put(self, key: str, value: bytes, namespace: str = "") -> None:
        with self.lock:
            self.kv.setdefault(namespace, {})[key] = value

    def kv_get(self, key: str, namespace: str = "") -> Optional[bytes]:
        with self.lock:
            return self.kv.get(namespace, {}).get(key)

    def kv_del(self, key: str, namespace: str = "") -> None:
        with self.lock:
            self.kv.get(namespace, {}).pop(key, None)

    def kv_keys(self, prefix: str = "", namespace: str = "") -> List[str]:
        with self.lock:
            return [k for k in self.kv.get(namespace, {}) if k.startswith(prefix)]
