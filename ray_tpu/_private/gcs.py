"""Global control state: node/actor/job/function/KV/placement-group tables.

In-process analogue of the reference's GCS server
(ray: src/ray/gcs/gcs_server/gcs_server.h:77) with the same table layout:
  * NodeTable   -- ray: gcs_node_manager.h:41
  * ActorTable  -- ray: gcs_actor_manager.h:280 (restart FSM at :258)
  * FunctionTable -- ray: python/ray/_private/function_manager.py (fn exports)
  * KV          -- ray: gcs_kv_manager.cc
  * PlacementGroupTable -- ray: gcs_placement_group_manager.h:223

The driver process hosts these tables; worker processes reach them through
their connection to the driver (the "DCN control plane"). A future multi-host
round promotes this object behind a gRPC service without changing callers.
"""

from __future__ import annotations

import threading

from ray_tpu._private import lock_watchdog
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

# Actor lifecycle states (ray: gcs_actor_manager.h FSM)
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


@dataclass
class NodeInfo:
    node_id: str
    resources: Dict[str, float]
    available: Dict[str, float]
    alive: bool = True
    labels: Dict[str, str] = field(default_factory=dict)
    is_head: bool = False


@dataclass
class ActorInfo:
    actor_id: str
    name: Optional[str]
    state: str = PENDING_CREATION
    node_id: Optional[str] = None
    worker_id: Optional[str] = None
    max_restarts: int = 0
    num_restarts: int = 0
    creation_spec: Any = None  # TaskSpec, kept for restarts
    death_cause: Optional[str] = None
    namespace: str = "default"
    # Head-split mode (ray: gcs_actor_manager detached actors + job owner):
    # owner_did names the attached driver that created the actor (None for
    # the in-process driver); non-detached actors die with their owner.
    owner_did: Optional[str] = None
    detached: bool = False


@dataclass
class PlacementGroupInfo:
    pg_id: str
    bundles: List[Dict[str, float]]
    strategy: str
    state: str = "PENDING"  # PENDING | CREATED | REMOVED
    # bundle index -> node_id
    bundle_nodes: Dict[int, str] = field(default_factory=dict)
    # bundle index -> remaining capacity inside the reserved bundle
    # (tasks scheduled into the PG consume bundle capacity, not node pool:
    #  ray: src/ray/raylet/placement_group_resource_manager.h)
    bundle_available: Dict[int, Dict[str, float]] = field(default_factory=dict)
    name: Optional[str] = None


class GlobalState:
    def __init__(self):
        self.lock = lock_watchdog.make_lock("GlobalState.lock", rlock=True)
        self.nodes: Dict[str, NodeInfo] = {}
        self.actors: Dict[str, ActorInfo] = {}
        self.named_actors: Dict[tuple, str] = {}  # (namespace, name) -> actor_id
        self.functions: Dict[str, bytes] = {}
        self.kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> {key: val}
        self.placement_groups: Dict[str, PlacementGroupInfo] = {}
        self.job_start_time = time.time()
        # Cluster-event channels on the SHARED pubsub abstraction
        # (ray: src/ray/pubsub/publisher.h:298 — same Publisher the
        # runtime's object-ready plane and serve's long-poll use).
        from ray_tpu._private.pubsub import Publisher

        self.publisher = Publisher()

    # -- events --------------------------------------------------------------

    def subscribe(self, channel: str, cb: Callable) -> None:
        self.publisher.subscribe(channel, None, cb)

    def publish(self, channel: str, *args) -> None:
        self.publisher.publish(channel, None, *args)

    # -- nodes ---------------------------------------------------------------

    def register_node(self, info: NodeInfo) -> None:
        with self.lock:
            self.nodes[info.node_id] = info
        self.publish("node_added", info.node_id)

    def remove_node(self, node_id: str) -> None:
        with self.lock:
            n = self.nodes.get(node_id)
            if n:
                n.alive = False
        self.publish("node_removed", node_id)

    def alive_nodes(self) -> List[NodeInfo]:
        with self.lock:
            return [n for n in self.nodes.values() if n.alive]

    # -- functions -----------------------------------------------------------

    def export_function(self, fn_id: str, blob: bytes) -> None:
        with self.lock:
            self.functions[fn_id] = blob

    def get_function(self, fn_id: str) -> Optional[bytes]:
        with self.lock:
            return self.functions.get(fn_id)

    # -- actors --------------------------------------------------------------

    def register_actor(self, info: ActorInfo) -> None:
        with self.lock:
            self.actors[info.actor_id] = info
            if info.name:
                key = (info.namespace, info.name)
                if key in self.named_actors:
                    raise ValueError(f"actor name {info.name!r} already taken")
                self.named_actors[key] = info.actor_id

    def get_actor(self, actor_id: str) -> Optional[ActorInfo]:
        with self.lock:
            return self.actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str = "default") -> Optional[ActorInfo]:
        with self.lock:
            aid = self.named_actors.get((namespace, name))
            return self.actors.get(aid) if aid else None

    def set_actor_state(self, actor_id: str, state: str, **kw) -> None:
        with self.lock:
            a = self.actors.get(actor_id)
            if not a:
                return
            a.state = state
            for k, v in kw.items():
                setattr(a, k, v)
            if state == DEAD and a.name:
                self.named_actors.pop((a.namespace, a.name), None)
        self.publish("actor_state", actor_id, state)

    # -- kv (ray: gcs_kv_manager.cc) ----------------------------------------

    def kv_put(self, key: str, value: bytes, namespace: str = "") -> None:
        with self.lock:
            self.kv.setdefault(namespace, {})[key] = value

    def kv_get(self, key: str, namespace: str = "") -> Optional[bytes]:
        with self.lock:
            return self.kv.get(namespace, {}).get(key)

    def kv_del(self, key: str, namespace: str = "") -> None:
        with self.lock:
            self.kv.get(namespace, {}).pop(key, None)

    def kv_keys(self, prefix: str = "", namespace: str = "") -> List[str]:
        with self.lock:
            return [k for k in self.kv.get(namespace, {}) if k.startswith(prefix)]
