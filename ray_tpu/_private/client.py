"""Core client: routes API calls to the driver Runtime or, inside a worker
process, over the control connection to the owner.

Mirrors the split in the reference where both drivers and workers link the
same CoreWorker library (ray: src/ray/core_worker/core_worker_process.h) and
the Python API is mode-agnostic (ray: python/ray/_private/worker.py:404).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization as ser
from ray_tpu._private.refs import ObjectRef
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu.exceptions import GetTimeoutError


def in_worker() -> bool:
    from ray_tpu._private import worker_proc

    return worker_proc.get_worker_runtime() is not None


def current_session() -> Optional[str]:
    """Session name of the active runtime (None if not initialized).

    Used to invalidate per-process caches (exported functions) across
    init/shutdown cycles, like the reference's per-job function table
    (ray: python/ray/_private/function_manager.py keyed by job id).
    """
    from ray_tpu._private import runtime as rt
    from ray_tpu._private.worker_proc import get_worker_runtime

    wr = get_worker_runtime()
    if wr is not None:
        return wr.session_name
    if rt.is_initialized():
        return rt.get_runtime().session_name
    return None


class CoreClient:
    """Facade over either the in-process Runtime (driver) or the worker's
    connection to it."""

    # -- driver/worker dispatch ---------------------------------------------

    def _rt(self):
        from ray_tpu._private.runtime import get_runtime

        return get_runtime()

    def _wr(self):
        from ray_tpu._private.worker_proc import get_worker_runtime

        return get_worker_runtime()

    # -- functions ----------------------------------------------------------

    def export_function(self, fn_id: str, blob: bytes) -> None:
        wr = self._wr()
        if wr is not None:
            wr.request("export_function", (fn_id, blob))
        else:
            self._rt().state.export_function(fn_id, blob)

    # -- tasks ---------------------------------------------------------------

    @staticmethod
    def _stamp_parent(spec: TaskSpec) -> None:
        from ray_tpu._private.worker_proc import current_task_id

        if spec.parent_task_id is None:
            spec.parent_task_id = current_task_id()
        from ray_tpu.util import tracing

        if tracing.is_enabled() and spec.trace_ctx is None:
            # The submit span's context rides the spec, so the executor's
            # run span parents to it across the process boundary.
            with tracing.span(
                f"submit::{spec.name}", attrs={"task_id": spec.task_id}
            ) as ctx:
                spec.trace_ctx = dict(ctx)

    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        self._stamp_parent(spec)
        wr = self._wr()
        if wr is not None:
            wr.note_escaped(spec.contained_refs)
            # Nested submissions push straight to a head-leased worker when
            # the task shape allows it (ray: direct_task_transport.h:75);
            # a denied/ineligible lease falls back to the queued head path.
            if wr.direct is not None:
                return_ids = wr.direct.submit_plain(spec)
                if return_ids is not None:
                    return [ObjectRef(oid, _count=False) for oid in return_ids]
            return_ids = wr.request("submit", spec)
        else:
            return_ids = self._rt().submit_task(spec)
        return [ObjectRef(oid) for oid in return_ids]

    def create_actor(self, spec: TaskSpec) -> str:
        self._stamp_parent(spec)
        wr = self._wr()
        if wr is not None:
            wr.note_escaped(spec.contained_refs)
            return wr.request("create_actor", spec)
        return self._rt().create_actor(spec)

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        self._stamp_parent(spec)
        wr = self._wr()
        if wr is not None:
            # Hot path: push straight to the actor's worker when eligible
            # (ray: direct_actor_task_submitter.h:67) — zero head messages.
            wr.note_escaped(spec.contained_refs)
            if wr.direct is not None:
                return_ids = wr.direct.submit(spec)
                if return_ids is not None:
                    # _count=False: the transport pre-counted these refs at
                    # submit (see DirectTransport.submit).
                    return [ObjectRef(oid, _count=False) for oid in return_ids]
            return_ids = wr.request("actor_call", spec)
        else:
            return_ids = self._rt().submit_actor_task(spec)
        return [ObjectRef(oid) for oid in return_ids]

    # -- objects -------------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        wr = self._wr()
        if wr is not None:
            oid = wr.put_value(value)
            return ObjectRef(oid)
        return self._rt().put(value)

    def get(self, refs, timeout: Optional[float] = None):
        wr = self._wr()
        if wr is None:
            return self._rt().get(refs, timeout)
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        values = []
        deadline = None if timeout is None else time.monotonic() + timeout
        for r in refs:
            t = None if deadline is None else max(0.0, deadline - time.monotonic())
            try:
                values.append(self._worker_get_one(wr, r.id, t))
            except Exception:
                raise
        return values[0] if single else values

    def _worker_get_one(self, wr, oid: str, timeout: Optional[float]):
        # One resolution path for arg resolution AND user-level get: local
        # node store, then the owner, which replies inline / local-shm /
        # pull-endpoints (cross-node transfer).
        return wr.get_value(oid, timeout=timeout)

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        wr = self._wr()
        if wr is None:
            return self._rt().wait_refs(refs, num_returns, timeout)
        import queue as _q

        # Event-driven: the owner parks each request until num_returns are
        # ready (or its chunk timer lapses) and replies once — no poll loop.
        # Chunking (30s server-side timers + a transport guard) bounds the
        # damage of a lost reply: the next chunk re-asks instead of hanging.
        deadline = None if timeout is None else time.monotonic() + timeout
        oids = [r.id for r in refs]
        # Locally-owned direct results aren't visible to the owner until
        # promoted: promote any involved in a wait so one head-side wait
        # covers the whole list (wait is not the per-call hot path).
        wr.note_escaped([oid for oid in oids if wr.direct is not None
                         and wr.direct.owns(oid)])
        flags = [False] * len(refs)
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            chunk = 30.0 if remaining is None else max(min(remaining, 30.0), 0.0)
            try:
                flags = wr.request(
                    "wait_objects", (oids, num_returns, chunk), timeout=chunk + 10
                )
            except _q.Empty:
                pass  # lost reply: fall through and re-ask (or give up)
            if sum(flags) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
        ready = [r for r, f in zip(refs, flags) if f]
        ready = ready[:num_returns] if len(ready) >= num_returns else ready
        ready_set = {r.id for r in ready}
        not_ready = [r for r in refs if r.id not in ready_set]
        return ready, not_ready

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        wr = self._wr()
        if wr is not None:
            # Direct calls are tracked caller-side only: cancel rides the
            # peer socket with queued-drop semantics.  force=True is
            # deliberately NOT escalated here — the reference likewise
            # rejects force-cancellation of actor tasks (the interruption
            # primitive for a stuck actor is kill, not cancel).
            if wr.direct is not None and wr.direct.cancel(ref.id):
                return
            wr.request("cancel", (ref.id, force))
        else:
            self._rt().cancel(ref, force)

    # -- actors --------------------------------------------------------------

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        wr = self._wr()
        if wr is not None:
            wr.request("kill_actor", (actor_id, no_restart))
        else:
            self._rt().kill_actor(actor_id, no_restart)

    def get_named_actor(
        self, name: str, namespace: Optional[str]
    ) -> Tuple[str, List[str], int]:
        """(actor_id, method_names, actor_max_concurrency)."""
        wr = self._wr()
        if wr is not None:
            return wr.request("get_actor_named", (name, namespace))
        rt = self._rt()
        return rt._handle_req("driver", -1, "get_actor_named", (name, namespace))

    # -- kv ------------------------------------------------------------------

    def kv_put(self, key: str, value: bytes, namespace: str = "") -> None:
        wr = self._wr()
        if wr is not None:
            wr.request("kv_put", (key, value, namespace))
        else:
            self._rt().state.kv_put(key, value, namespace)

    def kv_get(self, key: str, namespace: str = "") -> Optional[bytes]:
        wr = self._wr()
        if wr is not None:
            return wr.request("kv_get", (key, namespace))
        return self._rt().state.kv_get(key, namespace)

    # -- placement groups ----------------------------------------------------

    def pg_create(self, bundles, strategy, name=None) -> str:
        wr = self._wr()
        if wr is not None:
            # Mint the id CLIENT-side: a request retried across a head
            # bounce then dedupes instead of double-reserving bundles.
            from ray_tpu._private import ids as _ids

            pg_id = _ids.placement_group_id()
            return wr.request("pg_create", (bundles, strategy, name, pg_id))
        return self._rt().create_placement_group(bundles, strategy, name).pg_id

    def pg_state(self, pg_id: str) -> Optional[str]:
        wr = self._wr()
        if wr is not None:
            return wr.request("pg_state", pg_id)
        pg = self._rt().state.placement_groups.get(pg_id)
        return pg.state if pg else None

    def pg_remove(self, pg_id: str) -> None:
        wr = self._wr()
        if wr is not None:
            wr.request("pg_remove", pg_id)
        else:
            self._rt().remove_placement_group(pg_id)

    def pg_info(self, pg_id: str) -> Optional[Dict]:
        """Elastic-gang introspection: state + generation + shrunk size +
        scale-up cue (see Runtime.pg_info)."""
        wr = self._wr()
        if wr is not None:
            return wr.request("pg_info", pg_id)
        return self._rt().pg_info(pg_id)

    def pg_reshape(self, pg_id: str) -> bool:
        """Ask the head to re-mesh a shrunk MESH gang back to full size."""
        wr = self._wr()
        if wr is not None:
            return bool(wr.request("pg_reshape", pg_id))
        return self._rt().pg_reshape(pg_id)

    # -- cluster -------------------------------------------------------------

    def cluster_resources(self) -> Dict[str, float]:
        wr = self._wr()
        if wr is not None:
            return wr.request("cluster_resources", None)
        return self._rt().cluster_resources()

    def available_resources(self) -> Dict[str, float]:
        wr = self._wr()
        if wr is not None:
            return wr.request("available_resources", None)
        return self._rt().available_resources()


client = CoreClient()


_EMPTY_ARGS_BLOB = None


def build_args_blob(args: tuple, kwargs: dict):
    """Serialize call args; returns (packed_blob, contained_ids, top_level_dep_ids)."""
    global _EMPTY_ARGS_BLOB
    if not args and not kwargs:
        # No-arg calls (fan-outs of nullary tasks are a whole bench shape)
        # share one immutable pre-packed blob instead of re-serializing
        # ((), {}) per call.
        blob = _EMPTY_ARGS_BLOB
        if blob is None:
            payload, buffers, _ = ser.serialize(((), {}))
            blob = _EMPTY_ARGS_BLOB = bytes(ser.pack(payload, buffers))
        return blob, [], []
    payload, buffers, contained = ser.serialize((args, kwargs))
    deps = [a.id for a in args if isinstance(a, ObjectRef)]
    deps += [v.id for v in kwargs.values() if isinstance(v, ObjectRef)]
    return bytes(ser.pack(payload, buffers)), contained, deps
