"""One pub/sub channel abstraction for every push mechanism.

ray: src/ray/pubsub/publisher.h:298 (Publisher with per-channel subscriber
state) + subscriber.h:70 (long-poll delivery).  Rounds 1-3 grew three
bespoke push mechanisms — object-ready wait tokens in the owner store,
ad-hoc callback lists in the GCS tables, and a condition-variable long
poll in the serve controller.  They are all subscriptions:

  * `Publisher` — channels keyed by (channel, key); `once=True`
    subscriptions fire on the next publish then drop (the parking
    primitive behind get/wait/dep-resolution), persistent ones fire on
    every publish (GCS event listeners, log fan-out).  `deferred=True`
    marks callbacks the PUBLISHER'S CALLER must run after releasing its
    own locks (a parked get's reply does store reads that must not run
    under the runtime lock) — publish returns them instead of calling.
  * `LongPollHost` — the blocking long-poll pattern over Publisher
    (ray: serve _private/long_poll.py:185): callers park on a key until a
    predicate turns true or their chunk timeout lapses.

Delivery is in-process for head-side subscribers, and CROSS-PROCESS via
`remote_hook`: the head's Runtime installs a hook that fans every publish
out to workers/drivers that sent a ("subscribe", channel, key) frame —
pushes ride the existing framed control conns as ("pub", channel, key,
args) (ray: subscriber.h:70 long-polls the publisher over the network;
ours pushes over the already-open conn, same delivery guarantee, one
less round trip).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class Subscription:
    __slots__ = ("channel", "key", "cb", "once", "deferred", "active")

    def __init__(self, channel: str, key: Any, cb: Callable, once: bool,
                 deferred: bool):
        self.channel = channel
        self.key = key
        self.cb = cb
        self.once = once
        self.deferred = deferred
        self.active = True


class Publisher:
    """Thread-safe; inline callbacks run on the publishing thread (under
    whatever locks the publisher's caller holds — subscribe with
    deferred=True when the callback must not)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: Dict[Tuple[str, Any], List[Subscription]] = {}
        # Cross-process fan-out: called as remote_hook(channel, key, args)
        # on EVERY publish, after local dispatch (installed by the head's
        # Runtime; None in workers/tests).
        self.remote_hook = None

    def subscribe(self, channel: str, key: Any, cb: Callable, *,
                  once: bool = False, deferred: bool = False) -> Subscription:
        sub = Subscription(channel, key, cb, once, deferred)
        with self._lock:
            self._subs.setdefault((channel, key), []).append(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        sub.active = False
        with self._lock:
            lst = self._subs.get((sub.channel, sub.key))
            if lst is not None:
                try:
                    lst.remove(sub)
                except ValueError:
                    pass
                if not lst:
                    self._subs.pop((sub.channel, sub.key), None)

    def publish(self, channel: str, key: Any, *args) -> List[Callable]:
        """Fire subscriptions for (channel, key).  Inline callbacks run
        here (exceptions swallowed per-subscriber, as the reference's
        publisher isolates subscriber failures); deferred callbacks are
        RETURNED for the caller to invoke outside its locks."""
        hook = self.remote_hook
        if hook is not None:
            try:
                hook(channel, key, args)
            except Exception:
                import traceback

                traceback.print_exc()
        with self._lock:
            lst = self._subs.get((channel, key))
            if not lst:
                return []
            fired = [s for s in lst if s.active]
            keep = [s for s in lst if s.active and not s.once]
            if keep:
                self._subs[(channel, key)] = keep
            else:
                self._subs.pop((channel, key), None)
        deferred = []
        for s in fired:
            if s.deferred:
                deferred.append(s.cb)
            else:
                try:
                    s.cb(*args)
                except Exception:
                    import traceback

                    traceback.print_exc()
        return deferred

    def num_subscribers(self, channel: str, key: Any = None) -> int:
        with self._lock:
            if key is not None:
                return len(self._subs.get((channel, key), ()))
            return sum(
                len(v) for (c, _k), v in self._subs.items() if c == channel
            )


class LongPollHost:
    """Blocking long-poll over Publisher (ray: LongPollHost.listen_for_change,
    serve/_private/long_poll.py:185)."""

    def __init__(self, publisher: Optional[Publisher] = None,
                 channel: str = "longpoll"):
        self._pub = publisher or Publisher()
        self._channel = channel

    def notify(self, key: Any, *args) -> None:
        self._pub.publish(self._channel, key, *args)

    def wait_for_change(self, key: Any, predicate: Callable[[], bool],
                        timeout: float) -> bool:
        """Park until predicate() is true or the timeout lapses; returns
        the final predicate value.  Subscribe-then-recheck closes the race
        between the check and a concurrent notify."""
        deadline = time.monotonic() + timeout
        while True:
            if predicate():
                return True
            ev = threading.Event()
            sub = self._pub.subscribe(
                self._channel, key, lambda *a: ev.set(), once=True
            )
            if predicate():
                self._pub.unsubscribe(sub)
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not ev.wait(remaining):
                self._pub.unsubscribe(sub)
                return predicate()
