"""TaskSpec: the unit handed from submitter to scheduler to executor.

Analogue of the reference's TaskSpecification
(ray: src/ray/common/task/task_spec.h) -- carries identity, the function to
run, serialized args, resource demands and scheduling policy. Ours is a plain
dataclass because the control plane speaks pickled Python over per-host
connections instead of protobuf-over-gRPC (that boundary returns when the
multi-host DCN transport lands).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class TaskSpec:
    task_id: str
    name: str
    fn_id: str
    args_blob: bytes  # packed serialize((args, kwargs))
    # All ObjectRef ids reachable from the args (borrowed for the task's
    # lifetime, ray: reference_count.h borrow semantics).
    contained_refs: List[str] = field(default_factory=list)
    # Top-level arg refs: scheduling dependencies resolved to values before
    # execution (ray semantics: top-level refs resolve, nested pass through).
    deps: List[str] = field(default_factory=list)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})
    # Actor bits
    actor_id: Optional[str] = None
    method_name: Optional[str] = None
    is_actor_creation: bool = False
    actor_name: Optional[str] = None
    actor_namespace: Optional[str] = None
    # Tracing (ray: tracing_helper.py injects context into task specs;
    # ProfileEvent parentage): the submitting task, None for driver submits.
    parent_task_id: Optional[str] = None
    # OTel-style trace context injected at submission when tracing is on
    # (ray: _DictPropagator.inject_current_context, tracing_helper.py:160).
    trace_ctx: Optional[Dict[str, str]] = None
    actor_method_names: Optional[List[str]] = None
    max_concurrency: int = 1
    # The ACTOR's method concurrency (creation tasks run ordered with
    # max_concurrency=1, so the actor-wide setting needs its own field —
    # named-actor lookups return it so a get_actor() handle schedules onto
    # the same executor as the creator's handle).
    actor_max_concurrency: int = 1
    # Default per-method retry budget across actor restarts (ray:
    # max_task_retries on @ray.remote actor classes).
    actor_max_task_retries: int = 0
    max_restarts: int = 0
    is_async_actor: bool = False
    # "detached": the actor outlives its creating driver (ray: actor
    # lifetime option, gcs_actor_manager detached registry).
    lifetime: Optional[str] = None
    # Retries / recovery (ray: src/ray/core_worker/task_manager.h:90)
    max_retries: int = 0
    retry_exceptions: bool = False
    attempt: int = 0
    # Scheduling (ray: python/ray/util/scheduling_strategies.py)
    scheduling_strategy: Any = None  # None | "DEFAULT" | "SPREAD" | strategy obj
    placement_group_id: Optional[str] = None
    placement_group_bundle_index: int = -1
    # Runtime env (subset: env_vars) (ray: python/ray/_private/runtime_env/)
    runtime_env: Optional[Dict[str, Any]] = None
    owner_id: str = "driver"

    def return_ids(self) -> List[str]:
        from ray_tpu._private.ids import object_id

        return [object_id(self.task_id, i) for i in range(self.num_returns)]

    def requires_dedicated_worker(self) -> bool:
        return bool(self.runtime_env and self.runtime_env.get("env_vars"))
