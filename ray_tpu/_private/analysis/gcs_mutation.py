"""Pass 5: GCS table mutations outside the journaled mutators.

The durability story (snapshot + append-only mutation journal,
gcs_storage.py) only holds while every actor/named-binding/job table
mutation flows through GlobalState's journaled mutators in
`ray_tpu/_private/gcs.py` — a direct dict write elsewhere (e.g.
`rt.state.actors[aid] = info`) would take effect in memory but never hit
the journal, and the mutation would silently NOT survive a head bounce:
exactly the class of gap the PR-1 chaos soak spent minutes finding.

This pass flags any write-shaped access to the journaled tables
(`actors`, `named_actors`, `jobs`) on a GlobalState-ish receiver (dotted
path whose owner terminates in `state`/`_state`/`gcs`) in any module
other than gcs.py itself:

  * subscript assignment / augmented assignment / `del`;
  * mutating method calls: pop/popitem/update/setdefault/clear.

Reads (subscript loads, `.get(...)`, iteration) are untouched — the state
API and snapshot writer read these tables directly by design.  Reviewed
exceptions go in allowlist.txt with a justification, same contract as the
other passes.

FORWARD-ONLY modules (the io-shard fabric, io_shard.py) get a stricter
rule: ANY write-shaped access on a `state`/`gcs`-ish owner — any table
name, plain attribute rebinding included — fails.  A shard process
exists to decode and forward; the single-writer invariant (all GCS
mutation in the head over the journaled path) is the entire reason conn
sharding is safe, so the lint makes a shard-side mutation a CI failure
rather than a soak-found durability hole.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu._private.analysis.common import (
    Violation,
    dotted_name,
    parse_file,
    terminal_name,
)

PASS = "gcs-mutation"

# The journaled tables (GlobalState attributes whose mutations must ride
# the journal).  `functions` joined in the telemetry PR (function exports
# are journaled so a lineage re-execution within the snapshot tick never
# hits "unknown function" — the PR-4 residual); `placement_groups` joined
# with elastic re-mesh (a RESHAPING episode must survive a head bounce or
# the gang wedges forever); kv stays snapshot-only by design (full-table
# capture every tick).
_JOURNALED_TABLES = frozenset({
    "actors", "named_actors", "jobs", "functions", "placement_groups",
})

# Mutating dict methods; everything else on the table is a read.
_MUTATING_METHODS = frozenset({"pop", "popitem", "update", "setdefault", "clear"})

# The one module allowed to write the tables (it owns the mutators).
_MUTATOR_MODULE = "ray_tpu/_private/gcs.py"

# Forwarding-only modules: shard processes may never mutate ANY state
# table (not just the journaled ones) — see the module docstring.
FORWARD_ONLY_MODULES = frozenset({"ray_tpu/_private/io_shard.py"})


def _table_ref(expr: ast.AST, any_table: bool = False) -> Optional[str]:
    """When `expr` is `<owner>.state.actors`-shaped (a journaled table on
    a GlobalState-ish owner), return its dotted name, else None.  With
    any_table (forward-only modules), every attribute on a state/gcs-ish
    owner counts."""
    if not isinstance(expr, ast.Attribute):
        return None
    if not any_table and expr.attr not in _JOURNALED_TABLES:
        return None
    owner = terminal_name(expr.value)
    if owner is None or owner.lstrip("_") not in ("state", "gcs"):
        return None
    return dotted_name(expr) or f"<expr>.{expr.attr}"


class _Scanner(ast.NodeVisitor):
    def __init__(self, rel: str):
        self.rel = rel
        # Forward-only modules: any state/gcs table, any write shape.
        self.any_table = rel in FORWARD_ONLY_MODULES
        self.scope: List[str] = []
        self.violations: List[Violation] = []

    def qualname(self) -> str:
        return ".".join(self.scope) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _flag(self, node: ast.AST, table: str, how: str) -> None:
        key = f"{PASS}:{self.rel}:{self.qualname()}:{table}:{how}"
        if self.any_table:
            msg = (
                f"{self.rel}:{node.lineno}: {how} on state table `{table}` "
                f"in {self.qualname()} — io-shard processes are FORWARDING "
                "ONLY: all GCS mutation stays in the head over the "
                "journaled single-writer path (this is what makes conn "
                "sharding safe)"
            )
        else:
            msg = (
                f"{self.rel}:{node.lineno}: direct {how} on journaled GCS "
                f"table `{table}` in {self.qualname()} — route through the "
                "journaled mutators in gcs.py (register_actor / "
                "set_actor_state / set_job_state) or justify in the "
                "allowlist; a direct write silently skips the durability "
                "journal"
            )
        self.violations.append(Violation(PASS, self.rel, node.lineno, key, msg))

    def _check_store_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Subscript):
            table = _table_ref(target.value, self.any_table)
            if table is not None:
                self._flag(target, table, "subscript write")
        elif isinstance(target, ast.Attribute) and self.any_table:
            # Forward-only modules: rebinding a table wholesale
            # (`rt.state.actors = {}`) is a mutation too.
            table = _table_ref(target, True)
            if table is not None:
                self._flag(target, table, "attribute write")
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_store_target(elt)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_store_target(target)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store_target(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                table = _table_ref(target.value, self.any_table)
                if table is not None:
                    self._flag(target, table, "del")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATING_METHODS:
            table = _table_ref(func.value, self.any_table)
            if table is not None:
                self._flag(node, table, f".{func.attr}()")
        self.generic_visit(node)


def scan_file(path: str, rel: str) -> List[Violation]:
    if rel == _MUTATOR_MODULE:
        return []  # the mutators themselves live here
    tree = parse_file(path)
    if tree is None:
        return []
    s = _Scanner(rel)
    s.visit(tree)
    return s.violations
